"""serving/ — the adapt-on-request meta-inference engine.

The load-bearing contracts:

* the multi-tenant serving path is BIT-EXACT vs the training-path eval
  forward (``make_eval_step`` / ``make_eval_multi_step``) for the same
  snapshot/support/query sets — including at pad-fraction > 0 and across
  bucket boundaries (pad tenants are masked zeros and provably inert);
* steady-state mixed-bucket traffic never retraces (the engine's strict
  ``RetraceDetector`` is primed by ``warmup()``);
* serving telemetry records are schema-valid (v8 ``serving`` kind) and
  ``cli inspect summary`` renders them — and never crashes on pre-v8
  logs;
* checkpoint loading for serving is READ-ONLY: no experiment-dir
  mutation of any kind (the training-owned restore path renames
  crash-leftover ``.old`` siblings back into place; serving must not).
"""

import json
import os
import threading

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.core import maml
from howtotrainyourmamlpytorch_tpu.serving import (
    AdaptRequest,
    MicroBatcher,
    RefreshDaemon,
    Replica,
    ReplicaRouter,
    ReplicaSet,
    ServingEngine,
    home_replica,
    load_servable_snapshot,
    partition_devices,
    request_fingerprint,
    serve_requests,
)
from howtotrainyourmamlpytorch_tpu.serving.batcher import group_requests
from howtotrainyourmamlpytorch_tpu.telemetry import schema as tel


def make_serving_cfg(**overrides):
    base = dict(
        dataset_name="omniglot_dataset",
        image_height=10,
        image_width=10,
        image_channels=1,
        num_classes_per_set=3,
        num_samples_per_class=1,
        num_target_samples=2,
        batch_size=4,
        cnn_num_filters=4,
        num_stages=2,
        per_step_bn_statistics=True,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        use_remat=False,
        serving_bucket_ladder=[1, 2, 4],
        serving_max_tenants_per_dispatch=4,
        compilation_cache_dir="",
    )
    base.update(overrides)
    return MAMLConfig(**base)


class _ListSink:
    def __init__(self):
        self.records = []

    def write(self, record):
        self.records.append(record)


@pytest.fixture(scope="module")
def cfg():
    return make_serving_cfg()


@pytest.fixture(scope="module")
def state(cfg):
    return maml.init_state(cfg)


@pytest.fixture(scope="module")
def engine(cfg, state):
    """One warmed engine shared by the module (shots buckets 1 and 2);
    warmup pays the whole compile bill once."""
    eng = ServingEngine(
        cfg, state, shots_buckets=(1, 2), sink=_ListSink(),
        strict_retrace=True,
    )
    eng.warmup()
    return eng


def _request(cfg, rng, shots=1, labeled=True, tenant_id=None):
    n, t = cfg.num_classes_per_set, cfg.num_target_samples
    h, w, c = cfg.im_shape
    return AdaptRequest(
        support_x=rng.randn(n, shots, h, w, c).astype(np.float32),
        support_y=np.tile(np.arange(n, dtype=np.int32)[:, None], (1, shots)),
        query_x=rng.randn(n, t, h, w, c).astype(np.float32),
        query_y=(
            np.tile(np.arange(n, dtype=np.int32)[:, None], (1, t))
            if labeled else None
        ),
        tenant_id=tenant_id,
    )


def _eval_batch_for(cfg, requests, bucket, shots):
    """The serve dispatch's padded batch, assembled for the eval path
    (pad slots zeros — eval computes garbage for them, which must not
    touch real tasks)."""
    n, t = cfg.num_classes_per_set, cfg.num_target_samples
    h, w, c = cfg.im_shape
    x_s = np.zeros((bucket, n, shots, h, w, c), np.float32)
    y_s = np.zeros((bucket, n, shots), np.int32)
    x_t = np.zeros((bucket, n, t, h, w, c), np.float32)
    y_t = np.zeros((bucket, n, t), np.int32)
    for i, req in enumerate(requests):
        x_s[i], y_s[i], x_t[i] = req.support_x, req.support_y, req.query_x
        if req.query_y is not None:
            y_t[i] = req.query_y
    return x_s, y_s, x_t, y_t


# -- bit-exactness vs the eval path ------------------------------------------


def test_serve_full_bucket_bit_exact_vs_eval(cfg, state, engine):
    """A full dispatch (no padding) reproduces the eval forward
    bit-for-bit: softmax predictions, per-tenant accuracy, per-tenant
    loss — against both the plain eval step and the fused multi-step."""
    rng = np.random.RandomState(0)
    reqs = [_request(cfg, rng, tenant_id=f"t{i}") for i in range(4)]
    dr = engine.serve_group(reqs)
    assert dr.bucket == 4 and dr.tenants == 4

    x_s, y_s, x_t, y_t = _eval_batch_for(cfg, reqs, 4, 1)
    eval_step = jax.jit(maml.make_eval_step(cfg))
    metrics, preds = eval_step(state, x_s, y_s, x_t, y_t)
    preds = np.asarray(preds)
    for i, res in enumerate(dr.results):
        assert np.array_equal(res.preds, preds[i])
    # masked metrics over a full bucket == eval's plain means
    assert dr.metrics["loss"] == pytest.approx(
        float(metrics["loss"]), rel=1e-6
    )
    assert dr.metrics["accuracy"] == pytest.approx(
        float(metrics["accuracy"]), rel=1e-6
    )

    multi = jax.jit(maml.make_eval_multi_step(cfg, with_preds=True))
    _, preds_k = multi(state, *[a[None] for a in (x_s, y_s, x_t, y_t)])
    assert np.array_equal(
        np.stack([r.preds for r in dr.results]), np.asarray(preds_k)[0]
    )


def test_padding_inert_across_bucket_boundaries(cfg, state, engine):
    """Every partial group size (pad fraction > 0, all bucket
    boundaries): real tenants' predictions are bit-identical to the eval
    forward over the same padded batch, and the masked metrics aggregate
    ONLY the real tenants."""
    rng = np.random.RandomState(1)
    eval_step = jax.jit(maml.make_eval_step(cfg))
    for n_real, bucket in ((1, 1), (2, 2), (3, 4), (4, 4)):
        reqs = [_request(cfg, rng) for _ in range(n_real)]
        dr = engine.serve_group(reqs)
        assert dr.bucket == bucket and dr.tenants == n_real
        x_s, y_s, x_t, y_t = _eval_batch_for(cfg, reqs, bucket, 1)
        _, preds = eval_step(state, x_s, y_s, x_t, y_t)
        preds = np.asarray(preds)
        for i, res in enumerate(dr.results):
            assert np.array_equal(res.preds, preds[i]), (n_real, bucket, i)
        # the masked aggregates match the per-tenant values of the REAL
        # tenants only — pad tenants contribute exactly zero
        losses = [r.loss for r in dr.results]
        accs = [r.accuracy for r in dr.results]
        assert dr.metrics["loss"] == pytest.approx(
            float(np.sum(np.float32(losses)) / np.float32(n_real)),
            rel=1e-6,
        )
        assert dr.metrics["accuracy"] == pytest.approx(
            float(np.sum(np.float32(accs)) / np.float32(n_real)), rel=1e-6
        )


def test_pad_content_cannot_perturb_real_tenants(cfg, engine):
    """The same real tenants dispatched against DIFFERENT pad content
    (zeros vs a copied real tenant riding as actual data in eval) yield
    identical outputs — vmap tenant independence, the property the
    padding design rests on."""
    rng = np.random.RandomState(2)
    reqs = [_request(cfg, rng) for _ in range(3)]
    dr_padded = engine.serve_group(reqs)  # bucket 4: one zero pad slot
    # now fill the 4th slot with a real request (no padding at all)
    dr_full = engine.serve_group(reqs + [_request(cfg, rng)])
    for a, b in zip(dr_padded.results, dr_full.results[:3]):
        assert np.array_equal(a.preds, b.preds)
        assert a.loss == b.loss and a.accuracy == b.accuracy


def test_second_shots_bucket_bit_exact(cfg, state, engine):
    """The shots axis of the bucket ladder: a 2-shot request rides its
    own compiled program and still reproduces the eval forward exactly."""
    rng = np.random.RandomState(3)
    reqs = [_request(cfg, rng, shots=2) for _ in range(2)]
    dr = engine.serve_group(reqs)
    assert dr.shots == 2 and dr.bucket == 2
    x_s, y_s, x_t, y_t = _eval_batch_for(cfg, reqs, 2, 2)
    _, preds = jax.jit(maml.make_eval_step(cfg))(state, x_s, y_s, x_t, y_t)
    for i, res in enumerate(dr.results):
        assert np.array_equal(res.preds, np.asarray(preds)[i])


# -- retrace discipline ------------------------------------------------------


def test_mixed_bucket_traffic_never_retraces(cfg, engine):
    """Sustained mixed traffic (every group size x both shots buckets,
    labeled and label-free) stays on the warmed program set: the STRICT
    retrace detector observes zero new signatures (it would raise)."""
    rng = np.random.RandomState(4)
    before = engine.retrace_detector.retrace_count
    for round_i in range(3):
        for size in (1, 2, 3, 4):
            for shots in (1, 2):
                reqs = [
                    _request(cfg, rng, shots=shots,
                             labeled=(round_i + size) % 2 == 0)
                    for _ in range(size)
                ]
                engine.serve_group(reqs)
    assert engine.retrace_detector.retrace_count == before == 0


def test_unlabeled_requests_get_predictions_only(cfg, engine):
    rng = np.random.RandomState(5)
    res = engine.serve_group([_request(cfg, rng, labeled=False)]).results[0]
    assert res.preds.shape == (
        cfg.num_classes_per_set * cfg.num_target_samples,
        cfg.num_classes_per_set,
    )
    assert res.loss is None and res.accuracy is None


def test_unlabeled_tenants_excluded_from_masked_metrics(cfg, engine):
    """A label-free tenant's y_t slot is fabricated zeros — the metric
    mask must exclude it (scoring made-up labels would poison the
    aggregate), while its PREDICTIONS are identical to the labeled twin's
    (predictions never read labels)."""
    rng = np.random.RandomState(12)
    labeled = [_request(cfg, rng) for _ in range(2)]
    unlabeled = AdaptRequest(
        support_x=labeled[0].support_x.copy(),
        support_y=labeled[0].support_y.copy(),
        query_x=labeled[0].query_x.copy(),
        query_y=None,
    )
    dr_mixed = engine.serve_group([labeled[0], labeled[1], unlabeled])
    dr_labeled = engine.serve_group(labeled + [labeled[0]])
    # aggregate over the 2 labeled tenants only
    assert dr_mixed.metrics["loss"] == pytest.approx(
        float(np.sum(np.float32(
            [r.loss for r in dr_mixed.results[:2]]
        )) / np.float32(2)),
        rel=1e-6,
    )
    # the unlabeled tenant's predictions match its labeled twin's exactly
    assert np.array_equal(
        dr_mixed.results[2].preds, dr_labeled.results[2].preds
    )


# -- request validation ------------------------------------------------------


def test_engine_rejects_bad_geometry(cfg, engine):
    rng = np.random.RandomState(6)
    good = _request(cfg, rng)
    with pytest.raises(ValueError, match="support_x"):
        engine.serve_group([AdaptRequest(
            support_x=good.support_x[:, :, :5],  # wrong image height
            support_y=good.support_y,
            query_x=good.query_x,
        )])
    with pytest.raises(ValueError, match="shots"):
        engine.serve_group([_request(cfg, rng, shots=3)])  # not a bucket
    with pytest.raises(ValueError, match="one shots bucket"):
        engine.serve_group([_request(cfg, rng, shots=1),
                            _request(cfg, rng, shots=2)])
    with pytest.raises(ValueError, match="exceed"):
        engine.serve_group([_request(cfg, rng) for _ in range(5)])
    with pytest.raises(ValueError, match="at least one"):
        engine.serve_group([])


def test_serving_config_validation():
    with pytest.raises(ValueError, match="serving_bucket_ladder"):
        make_serving_cfg(serving_bucket_ladder=[2, 2, 4])
    with pytest.raises(ValueError, match="serving_bucket_ladder"):
        make_serving_cfg(serving_bucket_ladder=[])
    with pytest.raises(ValueError, match="serving_bucket_ladder"):
        make_serving_cfg(serving_bucket_ladder=[0, 2])
    with pytest.raises(ValueError, match="serving_max_tenants_per_dispatch"):
        make_serving_cfg(serving_max_tenants_per_dispatch=8)  # > max ladder
    with pytest.raises(ValueError, match="serving_max_wait_ms"):
        make_serving_cfg(serving_max_wait_ms=-1.0)
    # JSON-borne integral floats coerce
    c = make_serving_cfg(serving_bucket_ladder=[1.0, 2.0, 4.0])
    assert c.serving_bucket_ladder == [1, 2, 4]
    # the PR 13 fast-path knobs validate at config time
    with pytest.raises(ValueError, match="serving_ingest"):
        make_serving_cfg(serving_ingest="int4")
    with pytest.raises(ValueError, match="serving_adapted_cache_size"):
        make_serving_cfg(serving_adapted_cache_size=-1)
    with pytest.raises(ValueError, match="cifar"):
        make_serving_cfg(dataset_name="cifar10", serving_ingest="uint8")
    c = make_serving_cfg(serving_ingest="uint8",
                         serving_adapted_cache_size=4.0)
    assert c.serving_adapted_cache_size == 4  # JSON float coercion


# -- batching policy ---------------------------------------------------------


def test_group_requests_policy(cfg):
    rng = np.random.RandomState(7)
    reqs = [
        _request(cfg, rng, shots=1), _request(cfg, rng, shots=2),
        _request(cfg, rng, shots=1), _request(cfg, rng, shots=1),
        _request(cfg, rng, shots=2),
    ]
    groups = group_requests(reqs, max_tenants=2)
    # stable within a shots bucket, chunked at max_tenants
    assert groups == [[0, 2], [3], [1, 4]]
    assert group_requests([], 3) == []
    with pytest.raises(ValueError):
        group_requests(reqs, 0)


def test_serve_requests_aligns_results(cfg, engine):
    rng = np.random.RandomState(8)
    reqs = [
        _request(cfg, rng, shots=(i % 2) + 1, tenant_id=f"t{i}")
        for i in range(5)
    ]
    results, dispatches = serve_requests(engine, reqs)
    assert [r.tenant_id for r in results] == [f"t{i}" for i in range(5)]
    assert sum(d.tenants for d in dispatches) == 5
    # a re-dispatch of the same group reproduces each tenant exactly
    # (same bucket width; cross-WIDTH re-dispatch is only ulp-close —
    # XLA's per-task codegen is width-dependent, the caveat core/maml.py
    # documents — which is why the bit-exactness contract is pinned
    # against the eval path at matching width, not across buckets)
    group3 = [r for r in reqs if r.shots == 2]
    redo = engine.serve_group(group3).results
    assert np.array_equal(results[3].preds, redo[1].preds)


def test_micro_batcher_full_batch_and_wait(cfg, engine):
    """A full queue dispatches as ONE multi-tenant dispatch; a lone
    request dispatches once its max-wait expires; close() drains."""
    rng = np.random.RandomState(9)
    sink = engine.sink
    batcher = MicroBatcher(engine, max_tenants=2, max_wait_ms=10_000)
    try:
        n_before = len(sink.records)
        p1 = batcher.submit(_request(cfg, rng, tenant_id="a"))
        p2 = batcher.submit(_request(cfg, rng, tenant_id="b"))
        r1, r2 = p1.get(timeout=30), p2.get(timeout=30)
        assert r1.tenant_id == "a" and r2.tenant_id == "b"
        two = [
            r for r in sink.records[n_before:]
            if r.get("kind") == "serving" and r.get("event") == "dispatch"
        ]
        assert len(two) == 1 and two[0]["tenants"] == 2
        assert two[0]["queue_ms"] >= 0
    finally:
        batcher.close()
    # max-wait path: a lone request must not wait for a full batch
    batcher = MicroBatcher(engine, max_tenants=4, max_wait_ms=5)
    try:
        res = batcher.submit(_request(cfg, rng, tenant_id="solo")).get(
            timeout=30
        )
        assert res.tenant_id == "solo"
    finally:
        batcher.close()
    with pytest.raises(RuntimeError, match="closed"):
        batcher.submit(_request(cfg, rng))


def test_ripe_group_picks_most_overdue_queue(cfg, engine):
    """The dispatcher pops the ripe queue whose HEAD waited longest —
    oldest-first ACROSS shots buckets, so a continuously full low-shots
    queue cannot starve another bucket past its max-wait promise."""
    from howtotrainyourmamlpytorch_tpu.serving.batcher import _Pending

    rng = np.random.RandomState(13)
    batcher = MicroBatcher(engine, max_tenants=2, max_wait_ms=10_000)
    try:
        now = __import__("time").perf_counter()
        with batcher._cond:
            # shots=1: FULL queue, but younger; shots=2: expired older head
            batcher._queues[1] = [
                _Pending(_request(cfg, rng, shots=1), enqueued=now - 1.0),
                _Pending(_request(cfg, rng, shots=1), enqueued=now - 1.0),
            ]
            batcher._queues[2] = [
                _Pending(_request(cfg, rng, shots=2), enqueued=now - 60.0),
            ]
            group = batcher._ripe_group()
            assert group is not None and len(group) == 1
            assert group[0].request.shots == 2  # the most-overdue head won
            batcher._queues.clear()  # don't leave orphans for the worker
    finally:
        batcher.close()


def test_micro_batcher_validates_at_submit(cfg, engine):
    """A malformed request raises to ITS submitter at submit() time —
    never poisoning co-batched tenants with someone else's shape error —
    and degenerate batcher knobs are refused at construction."""
    rng = np.random.RandomState(15)
    batcher = MicroBatcher(engine, max_tenants=2, max_wait_ms=50)
    try:
        good = batcher.submit(_request(cfg, rng, tenant_id="ok"))
        with pytest.raises(ValueError, match="shots"):
            batcher.submit(_request(cfg, rng, shots=3))
        assert good.get(timeout=30).tenant_id == "ok"
    finally:
        batcher.close()
    with pytest.raises(ValueError, match="max_tenants"):
        MicroBatcher(engine, max_tenants=0)
    with pytest.raises(ValueError, match="max_wait_ms"):
        MicroBatcher(engine, max_wait_ms=-1)


def test_micro_batcher_concurrent_submitters(cfg, engine):
    """Requests submitted from many threads all complete and each gets
    ITS OWN result back (tenant ids round-trip)."""
    rng = np.random.RandomState(10)
    batcher = MicroBatcher(engine, max_tenants=4, max_wait_ms=2)
    requests = {
        f"t{i}": _request(cfg, rng, tenant_id=f"t{i}") for i in range(12)
    }
    out = {}

    def client(tid):
        out[tid] = batcher.submit(requests[tid]).get(timeout=60)

    threads = [
        threading.Thread(target=client, args=(tid,)) for tid in requests
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    batcher.close()
    assert set(out) == set(requests)
    for tid, res in out.items():
        assert res.tenant_id == tid


def test_failed_dispatch_kills_engine_with_root_cause(cfg, state):
    """A dispatch that fails after donation marks the engine DEAD: later
    requests raise the root cause immediately instead of a stream of
    unrelated donated-buffer errors masking it."""
    scfg = cfg.replace(
        serving_bucket_ladder=[1], serving_max_tenants_per_dispatch=1
    )
    eng = ServingEngine(scfg, state, strict_retrace=True)
    eng.warmup()
    rng = np.random.RandomState(14)
    boom = RuntimeError("device fell over")

    def _explode(*a, **k):
        raise boom

    eng._programs = {key: _explode for key in eng._programs}
    with pytest.raises(RuntimeError, match="device fell over"):
        eng.serve_group([_request(cfg, rng)])
    with pytest.raises(RuntimeError, match="ServingEngine is dead") as ei:
        eng.serve_group([_request(cfg, rng)])
    assert ei.value.__cause__ is boom
    # request-validation errors, by contrast, never kill an engine
    eng2 = ServingEngine(scfg, state, strict_retrace=True)
    eng2.warmup()
    with pytest.raises(ValueError):
        eng2.serve_group([_request(cfg, rng, shots=3)])
    assert eng2.serve_group([_request(cfg, rng)]).tenants == 1


def test_serve_bench_checkpoint_requires_config(tmp_path):
    """--checkpoint without --config is refused loudly: the checkpoint
    directory records no geometry, and a default-config template would
    fail the restore (or silently serve with the wrong inner steps)."""
    from howtotrainyourmamlpytorch_tpu.serving import bench as serve_bench

    with pytest.raises(SystemExit) as ei:
        serve_bench.main(["--checkpoint", str(tmp_path)])
    assert ei.value.code == 2


# -- telemetry ---------------------------------------------------------------


def test_serving_telemetry_records_validate(cfg, engine):
    """Every record the engine emitted through the module's traffic is
    schema-valid (v8 `serving` kind), and the rollup carries the latency
    percentiles + throughput."""
    records = engine.sink.records
    assert records, "engine traffic should have emitted records"
    for rec in records:
        tel.validate_record(rec)
        assert rec["kind"] == "serving" and rec["schema"] == tel.SCHEMA_VERSION
    rollup = engine.rollup()
    assert rollup["adapt_ms_p50"] > 0
    assert rollup["adapt_ms_p95"] >= rollup["adapt_ms_p50"]
    assert rollup["tenants_per_sec"] > 0
    assert rollup["retraces"] == 0
    rec = engine.sink.records[-1]
    tel.validate_record(rec)
    assert rec["event"] == "rollup"


def test_inspect_summary_renders_serving_line(cfg, engine, tmp_path, capsys):
    from howtotrainyourmamlpytorch_tpu.tools import telemetry_cli

    log = tmp_path / "serving.jsonl"
    with open(log, "w") as f:
        for rec in engine.sink.records:
            f.write(json.dumps(rec) + "\n")
    assert telemetry_cli.main(["summary", str(log)]) == 0
    out = capsys.readouterr().out
    assert "serving:" in out and "adapt p50" in out
    # machine-readable too
    assert telemetry_cli.main(["summary", str(log), "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["serving"]["dispatches"] >= 1
    assert payload["serving"]["adapt_ms_p50"] > 0


def test_inspect_summary_pre_v8_log_has_no_serving_line(capsys):
    """The serving line never crashes (or renders) on pre-v8 logs."""
    from howtotrainyourmamlpytorch_tpu.tools import telemetry_cli

    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures", "telemetry_v7_schema.jsonl"
    )
    assert telemetry_cli.main(["summary", fixture]) == 0
    out = capsys.readouterr().out
    assert "serving:" not in out


# -- read-only checkpoint loading (bugfix ride-along) ------------------------


def _dir_snapshot(root):
    out = {}
    for base, _, files in os.walk(root):
        for name in files:
            p = os.path.join(base, name)
            st = os.stat(p)
            out[os.path.relpath(p, root)] = (st.st_mtime_ns, st.st_size)
    return out


def test_servable_snapshot_load_is_read_only(cfg, state, tmp_path):
    """Loading a serving snapshot mutates NOTHING in the training run's
    directory — no file created, removed, renamed, or rewritten."""
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt

    save_dir = str(tmp_path / "saved_models")
    ckpt.save_checkpoint_async(
        save_dir, "train_model", 3, state,
        {"current_iter": 7}, clone_to="latest",
    )
    ckpt.wait_for_pending()
    before = _dir_snapshot(save_dir)
    loaded, exp_state = load_servable_snapshot(cfg, save_dir, "latest")
    assert exp_state["current_iter"] == 7
    assert _dir_snapshot(save_dir) == before
    for a, b in zip(
        jax.tree_util.tree_leaves(loaded), jax.tree_util.tree_leaves(state)
    ):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_readonly_load_reads_old_without_renaming(cfg, state, tmp_path):
    """A swap killed between its two renames leaves `<path>.old`; the
    READ-ONLY load restores FROM it without moving it (the training-owned
    load renames it back — that recovery belongs to the run's owner)."""
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt

    save_dir = str(tmp_path / "saved_models")
    ckpt.save_checkpoint_async(
        save_dir, "train_model", 2, state, {"current_iter": 5}
    )
    ckpt.wait_for_pending()
    path = os.path.join(save_dir, "train_model_2")
    os.rename(path, path + ".old")  # simulate the interrupted swap
    loaded, exp_state = load_servable_snapshot(cfg, save_dir, 2)
    assert exp_state["current_iter"] == 5
    assert os.path.isdir(path + ".old") and not os.path.isdir(path)
    # the training-owned path performs the recovery rename
    template = jax.eval_shape(lambda: maml.init_state(cfg))
    ckpt.load_checkpoint(save_dir, "train_model", 2, template)
    assert os.path.isdir(path) and not os.path.isdir(path + ".old")
    del loaded


def test_engine_serves_restored_snapshot_identically(cfg, state, engine,
                                                     tmp_path):
    """End to end: an engine over a checkpoint-restored snapshot serves
    bit-identically to the engine over the live training state."""
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt

    save_dir = str(tmp_path / "saved_models")
    ckpt.save_checkpoint_async(
        save_dir, "train_model", 1, state, {"current_iter": 1},
        clone_to="latest",
    )
    ckpt.wait_for_pending()
    restored, _ = load_servable_snapshot(cfg, save_dir)
    engine2 = ServingEngine(cfg, restored, shots_buckets=(1, 2),
                            strict_retrace=True)
    rng = np.random.RandomState(11)
    reqs = [_request(cfg, rng, tenant_id=f"t{i}") for i in range(3)]
    dr_live = engine.serve_group(reqs)
    dr_restored = engine2.serve_group(reqs)
    for a, b in zip(dr_live.results, dr_restored.results):
        assert np.array_equal(a.preds, b.preds)
        assert a.loss == b.loss


# -- batcher shutdown: drain + never-hang (PR 13 satellite) ------------------


def test_micro_batcher_close_serves_in_flight_requests(cfg, engine):
    """Requests still queued at close() (queue neither full nor expired)
    are SERVED during the drain — responses, not hanging futures."""
    rng = np.random.RandomState(20)
    batcher = MicroBatcher(engine, max_tenants=4, max_wait_ms=60_000)
    pendings = [
        batcher.submit(_request(cfg, rng, tenant_id=f"d{i}"))
        for i in range(3)
    ]
    batcher.close()  # drain: the group was neither full nor expired
    for i, p in enumerate(pendings):
        res = p.get(timeout=1)  # already set; must not block
        assert res.tenant_id == f"d{i}"


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning"
)
def test_micro_batcher_worker_crash_fails_futures_not_hangs(cfg, engine):
    """A worker crash OUTSIDE the dispatch try (the previously uncovered
    path) must fail every queued future with the root cause — and
    close() must sweep stragglers — instead of stranding submitters on
    futures nobody will ever set."""
    rng = np.random.RandomState(21)
    batcher = MicroBatcher(engine, max_tenants=2, max_wait_ms=60_000)
    boom = RuntimeError("scheduler exploded")

    def _explode():
        raise boom

    with batcher._cond:
        batcher._ripe_group = _explode  # crash before any dispatch
    p = batcher.submit(_request(cfg, rng, tenant_id="crash"))
    with pytest.raises(RuntimeError, match="worker crashed") as ei:
        p.get(timeout=30)
    assert ei.value.__cause__ is boom
    batcher.close()  # must not hang on the dead worker
    # a request that sneaks into the queues after the worker died is
    # failed by close()'s sweep, not stranded
    batcher2 = MicroBatcher(engine, max_tenants=2, max_wait_ms=60_000)
    batcher2._worker.join(timeout=0)  # worker alive
    with batcher2._cond:
        batcher2._ripe_group = _explode
    p2 = batcher2.submit(_request(cfg, rng))
    with pytest.raises(RuntimeError):
        p2.get(timeout=30)
    batcher2.close()


# -- ingest tiers: uint8 + index bit-exactness (PR 13 tentpole) --------------


def make_imagenet_serving_cfg(**overrides):
    """A mini-imagenet-family serving config: 3 channels, /255 decode,
    ImageNet stat-normalize AND the RGB->BGR flip — the decode rules the
    uint8/index LUT must reproduce bit-for-bit."""
    base = dict(
        dataset_name="mini_imagenet_full_size",
        image_height=8,
        image_width=8,
        image_channels=3,
        reverse_channels=True,
        num_classes_per_set=3,
        num_samples_per_class=1,
        num_target_samples=2,
        batch_size=4,
        cnn_num_filters=4,
        num_stages=2,
        number_of_training_steps_per_iter=2,
        number_of_evaluation_steps_per_iter=2,
        use_remat=False,
        serving_bucket_ladder=[1, 2],
        serving_max_tenants_per_dispatch=2,
        compilation_cache_dir="",
    )
    base.update(overrides)
    return MAMLConfig(**base)


def _host_decode(cfg, u8):
    """The host pipeline's decode of raw uint8 pixels (the reference the
    on-device LUT must match bit-for-bit)."""
    from howtotrainyourmamlpytorch_tpu.data.episodes import (
        augment_stack, decode_cached,
    )

    flat = np.asarray(u8).reshape((-1,) + u8.shape[-3:])
    out = augment_stack(cfg, decode_cached(cfg, flat), k=0, augment=False)
    return np.asarray(out, np.float32).reshape(u8.shape)


def _uint8_request(cfg, rng, shots=1, tenant_id=None):
    n, t = cfg.num_classes_per_set, cfg.num_target_samples
    h, w, c = cfg.im_shape
    return AdaptRequest(
        support_x=rng.randint(0, 256, (n, shots, h, w, c)).astype(np.uint8),
        support_y=np.tile(np.arange(n, dtype=np.int32)[:, None], (1, shots)),
        query_x=rng.randint(0, 256, (n, t, h, w, c)).astype(np.uint8),
        query_y=np.tile(np.arange(n, dtype=np.int32)[:, None], (1, t)),
        tenant_id=tenant_id,
    )


@pytest.mark.parametrize("cfg_factory", [
    lambda: make_serving_cfg(serving_bucket_ladder=[1, 2],
                             serving_max_tenants_per_dispatch=2),
    make_imagenet_serving_cfg,
], ids=["omniglot", "mini_imagenet_reverse_channels"])
def test_uint8_ingest_bit_exact_vs_f32(cfg_factory):
    """The uint8 serving ingest is bit-exact with the f32 path on both
    decode families (omniglot unrescaled cast; imagenet /255 +
    stat-normalize + RGB->BGR) — per-tenant preds, loss, accuracy AND
    the masked aggregates — while uploading ~4x fewer pixel bytes."""
    scfg = cfg_factory()
    state = maml.init_state(scfg)
    eng_f32 = ServingEngine(scfg, state, shots_buckets=(1,),
                            strict_retrace=True)
    eng_u8 = ServingEngine(scfg, state, shots_buckets=(1,),
                           strict_retrace=True, ingest="uint8")
    eng_f32.warmup()
    eng_u8.warmup()
    rng = np.random.RandomState(22)
    u8_reqs = [_uint8_request(scfg, rng, tenant_id=f"u{i}")
               for i in range(2)]
    f32_reqs = [
        AdaptRequest(
            support_x=_host_decode(scfg, r.support_x),
            support_y=r.support_y,
            query_x=_host_decode(scfg, r.query_x),
            query_y=r.query_y,
            tenant_id=r.tenant_id,
        )
        for r in u8_reqs
    ]
    dr_u8 = eng_u8.serve_group(u8_reqs)
    dr_f32 = eng_f32.serve_group(f32_reqs)
    for a, b in zip(dr_u8.results, dr_f32.results):
        assert np.array_equal(a.preds, b.preds)
        assert a.loss == b.loss and a.accuracy == b.accuracy
    assert dr_u8.metrics == dr_f32.metrics
    # the ingest tier's point: pixel bytes shrink 4x (labels/mask ride
    # along at int32, so the total is ≥3x)
    assert dr_f32.ingest_bytes >= 3 * dr_u8.ingest_bytes


def test_index_ingest_bit_exact_vs_f32_and_tiny_h2d(cfg, state):
    """The index-only ingest: store rows resident in HBM, per-dispatch
    H2D is the int32 gather + mask (<1KB here) — and the results are
    bit-exact with the f32 path fed the host-decoded pixels of the same
    store rows."""
    scfg = make_serving_cfg(serving_bucket_ladder=[1, 2],
                            serving_max_tenants_per_dispatch=2)
    st = maml.init_state(scfg)
    rng = np.random.RandomState(23)
    n, t = scfg.num_classes_per_set, scfg.num_target_samples
    h, w, c = scfg.im_shape
    store = rng.randint(0, 256, (64, h, w, c)).astype(np.uint8)
    eng_idx = ServingEngine(scfg, st, shots_buckets=(1,),
                            strict_retrace=True, ingest="index",
                            store=store)
    eng_f32 = ServingEngine(scfg, st, shots_buckets=(1,),
                            strict_retrace=True)
    eng_idx.warmup()
    eng_f32.warmup()
    from howtotrainyourmamlpytorch_tpu.serving import IndexRequest

    reqs, f32_reqs = [], []
    for i in range(2):
        si = rng.randint(0, 64, (n, 1)).astype(np.int32)
        qi = rng.randint(0, 64, (n, t)).astype(np.int32)
        reqs.append(IndexRequest(support_idx=si, query_idx=qi,
                                 tenant_id=f"ix{i}"))
        f32_reqs.append(AdaptRequest(
            support_x=_host_decode(scfg, store[si]),
            support_y=np.tile(np.arange(n, dtype=np.int32)[:, None], (1, 1)),
            query_x=_host_decode(scfg, store[qi]),
            query_y=np.tile(np.arange(n, dtype=np.int32)[:, None], (1, t)),
            tenant_id=f"ix{i}",
        ))
    dr_idx = eng_idx.serve_group(reqs)
    dr_f32 = eng_f32.serve_group(f32_reqs)
    for a, b in zip(dr_idx.results, dr_f32.results):
        assert np.array_equal(a.preds, b.preds)
        assert a.loss == b.loss and a.accuracy == b.accuracy
    assert dr_idx.ingest_bytes < 1024  # index-only dispatch: <1KB H2D
    assert dr_f32.ingest_bytes > 20 * dr_idx.ingest_bytes


def test_index_ingest_validation(cfg, state):
    scfg = make_serving_cfg(serving_bucket_ladder=[1],
                            serving_max_tenants_per_dispatch=1)
    st = maml.init_state(scfg)
    h, w, c = scfg.im_shape
    store = np.zeros((8, h, w, c), np.uint8)
    with pytest.raises(ValueError, match="registered store"):
        ServingEngine(scfg, st, ingest="index")
    with pytest.raises(ValueError, match="only applies"):
        ServingEngine(scfg, st, store=store)
    eng = ServingEngine(scfg, st, ingest="index", store=store,
                        strict_retrace=True)
    from howtotrainyourmamlpytorch_tpu.serving import IndexRequest

    n, t = scfg.num_classes_per_set, scfg.num_target_samples
    with pytest.raises(ValueError, match="out of range"):
        eng.serve_group([IndexRequest(
            support_idx=np.full((n, 1), 8, np.int32),  # == rows: OOB
            query_idx=np.zeros((n, t), np.int32),
        )])
    rng = np.random.RandomState(24)
    # a uint8 engine refuses float pixels instead of silently casting
    eng_u8 = ServingEngine(scfg, st, ingest="uint8", strict_retrace=True)
    with pytest.raises(ValueError, match="uint8"):
        eng_u8.serve_group([_request(scfg, rng)])


# -- adapted-params cache (PR 13 tentpole) -----------------------------------


@pytest.fixture(scope="module")
def cache_engine(cfg, state):
    """A warmed engine with the adapted-params cache on (shots bucket 1
    only, to bound the compile bill)."""
    eng = ServingEngine(
        cfg, state, shots_buckets=(1,), sink=_ListSink(),
        strict_retrace=True, cache_size=16,
    )
    eng.warmup()
    return eng


def test_cache_hit_bit_exact_same_width_matrix(cfg, state, cache_engine):
    """The hit/miss/width matrix: for every (group size, bucket) point,
    a repeat serve of the same tenants is ALL cache hits (predict-only
    program) and bit-exact with the original full adaptation — preds,
    loss, accuracy per tenant. Width is matched pairwise (repeat group
    == original group), the same width discipline every other
    bit-exactness contract in this file pins."""
    rng = np.random.RandomState(25)
    for n_real, bucket in ((1, 1), (2, 2), (3, 4), (4, 4)):
        reqs = [_request(cfg, rng, tenant_id=f"m{n_real}-{i}")
                for i in range(n_real)]
        dr_first = cache_engine.serve_group(reqs)
        assert dr_first.cache_hits == 0 and dr_first.bucket == bucket
        dr_repeat = cache_engine.serve_group(reqs)
        assert dr_repeat.cache_hits == n_real  # all hits: no inner loop
        for a, b in zip(dr_first.results, dr_repeat.results):
            assert np.array_equal(a.preds, b.preds)
            assert a.loss == b.loss and a.accuracy == b.accuracy
        assert dr_repeat.metrics == dr_first.metrics


def test_mixed_hit_miss_group_splits_cleanly(cfg, state, cache_engine):
    """A half-hit/half-miss group splits into one adapt + one predict
    dispatch; every tenant's result is bit-exact with its matched-width
    reference (hits vs their first adaptation, misses vs a fresh
    same-width adapt), and the telemetry records both program families."""
    rng = np.random.RandomState(26)
    known = [_request(cfg, rng, tenant_id=f"k{i}") for i in range(2)]
    dr_known = cache_engine.serve_group(known)  # adapt at bucket 2
    fresh = [_request(cfg, rng, tenant_id=f"f{i}") for i in range(2)]
    n_before = len(cache_engine.sink.records)
    dr_mixed = cache_engine.serve_group([known[0], fresh[0], known[1],
                                         fresh[1]])
    assert dr_mixed.cache_hits == 2 and dr_mixed.tenants == 4
    # hits (bucket 2 predict) reproduce their first adaptation (bucket 2
    # adapt) bit-for-bit
    assert np.array_equal(dr_mixed.results[0].preds,
                          dr_known.results[0].preds)
    assert np.array_equal(dr_mixed.results[2].preds,
                          dr_known.results[1].preds)
    assert dr_mixed.results[0].loss == dr_known.results[0].loss
    # misses (bucket 2 adapt) match a fresh cacheless engine at the same
    # width
    eng_plain = ServingEngine(cfg, state, shots_buckets=(1,),
                              strict_retrace=True)
    dr_fresh = eng_plain.serve_group(fresh)
    assert np.array_equal(dr_mixed.results[1].preds,
                          dr_fresh.results[0].preds)
    assert np.array_equal(dr_mixed.results[3].preds,
                          dr_fresh.results[1].preds)
    # both program families appear in the telemetry for the mixed group
    progs = [
        r.get("program") for r in cache_engine.sink.records[n_before:]
        if r.get("kind") == "serving" and r.get("event") == "dispatch"
    ]
    assert sorted(progs) == ["adapt", "predict"]


def test_cache_lru_evicts_and_readapts(cfg, state):
    """Eviction: a tenant pushed out of a capacity-2 LRU re-adapts on
    its next visit (miss), and its re-adapted results equal the
    originals at the same width (determinism of adaptation)."""
    scfg = make_serving_cfg(serving_bucket_ladder=[1, 2],
                            serving_max_tenants_per_dispatch=2)
    st = maml.init_state(scfg)
    eng = ServingEngine(scfg, st, shots_buckets=(1,),
                        strict_retrace=True, cache_size=2)
    eng.warmup()
    rng = np.random.RandomState(27)
    a, b, c = (_request(scfg, rng, tenant_id=t) for t in "abc")
    dr_a1 = eng.serve_group([a])
    eng.serve_group([b])
    eng.serve_group([c])  # evicts a (LRU capacity 2)
    assert len(eng._cache) == 2
    dr_a2 = eng.serve_group([a])
    assert dr_a2.cache_hits == 0  # evicted: full re-adaptation
    assert np.array_equal(dr_a1.results[0].preds, dr_a2.results[0].preds)
    dr_a3 = eng.serve_group([a])
    assert dr_a3.cache_hits == 1  # back in the cache
    assert np.array_equal(dr_a1.results[0].preds, dr_a3.results[0].preds)


def test_mixed_group_hits_survive_miss_eviction(cfg, state):
    """Regression: in a mixed group, inserting the MISSES' fast weights
    can evict the HITS' LRU entries before the predict dispatch reads
    them — the hit weights must be snapshotted at lookup time, so the
    group still serves (and stays bit-exact), never KeyErrors."""
    scfg = make_serving_cfg(serving_bucket_ladder=[1, 2, 4],
                            serving_max_tenants_per_dispatch=4)
    st = maml.init_state(scfg)
    eng = ServingEngine(scfg, st, shots_buckets=(1,),
                        strict_retrace=True, cache_size=2)
    eng.warmup()
    rng = np.random.RandomState(31)
    a, b, c, d = (_request(scfg, rng, tenant_id=t) for t in "abcd")
    dr_a = eng.serve_group([a, b])  # a, b cached (capacity 2: full)
    # hits {a, b} + misses {c, d}: the miss inserts evict a and b from
    # the capacity-2 LRU while the group is still in flight
    dr_mix = eng.serve_group([a, b, c, d])
    assert dr_mix.cache_hits == 2
    assert np.array_equal(dr_mix.results[0].preds, dr_a.results[0].preds)
    assert np.array_equal(dr_mix.results[1].preds, dr_a.results[1].preds)
    assert len(eng._cache) == 2  # c, d now occupy the LRU


def test_cache_key_scopes_snapshot_and_content(cfg, state):
    """The cache key covers support content AND the snapshot id: a
    different support set or a different checkpoint can never hit a
    stale entry."""
    scfg = make_serving_cfg(serving_bucket_ladder=[1],
                            serving_max_tenants_per_dispatch=1)
    st = maml.init_state(scfg)
    eng = ServingEngine(scfg, st, shots_buckets=(1,),
                        strict_retrace=True, cache_size=8,
                        snapshot_id="ckpt-1")
    eng.warmup()
    rng = np.random.RandomState(28)
    req = _request(scfg, rng, tenant_id="t")
    eng.serve_group([req])
    # same support, different queries: still a hit (the key is the
    # SUPPORT fingerprint — queries ride the predict program)
    req2 = AdaptRequest(
        support_x=req.support_x.copy(), support_y=req.support_y.copy(),
        query_x=rng.randn(*req.query_x.shape).astype(np.float32),
        query_y=req.query_y.copy(), tenant_id="t",
    )
    assert eng.serve_group([req2]).cache_hits == 1
    # perturbed support content: miss
    req3 = AdaptRequest(
        support_x=req.support_x + 1.0, support_y=req.support_y.copy(),
        query_x=req.query_x.copy(), query_y=req.query_y.copy(),
    )
    assert eng.serve_group([req3]).cache_hits == 0
    # same request against another snapshot id: a different engine's
    # cache can never confuse the two (keys differ by construction)
    eng2 = ServingEngine(scfg, st, shots_buckets=(1,),
                         strict_retrace=True, cache_size=8,
                         snapshot_id="ckpt-2")
    assert eng._cache_key(req, 1) != eng2._cache_key(req, 1)


def test_predict_program_has_no_inner_loop_ops(cfg):
    """The op-census proof that cache hits skip the inner loop: the
    predict-only program carries at most ONE forward's worth of
    matmul/conv ops — several times fewer than the adapt program, whose
    every inner step pays a support forward + backward + target forward.
    (The same censuses are pinned in CONTRACTS.json via `cli audit`.)"""
    from howtotrainyourmamlpytorch_tpu.analysis.auditor import (
        audit_system_programs,
    )

    b = cfg.batch_size
    reports = {
        r.program: r for r in audit_system_programs(
            cfg, programs=[f"serve_step[b={b}]", f"predict_step[b={b}]"]
        )
    }
    def matmul_ops(census):
        return census.get("dot", 0) + census.get("convolution", 0)

    serve_ops = matmul_ops(reports[f"serve_step[b={b}]"].census)
    predict_ops = matmul_ops(reports[f"predict_step[b={b}]"].census)
    assert predict_ops > 0
    # 2 eval inner steps x (support fwd + bwd(~2x fwd) + target fwd)
    # ≈ 8 forward-equivalents vs predict's single forward
    assert predict_ops * 4 <= serve_ops
    # and the predict program still honors the donation contract
    assert reports[f"predict_step[b={b}]"].ok


# -- zero-retrace across all three ingest modes ------------------------------


def test_steady_state_all_ingest_modes_never_retrace(cfg, state):
    """Sustained mixed traffic across the three ingest tiers AND the
    hit/miss split (every group size, repeat tenants interleaved with
    fresh ones) stays on the warmed program set: zero retraces under the
    strict detector on every engine."""
    scfg = make_serving_cfg(serving_bucket_ladder=[1, 2],
                            serving_max_tenants_per_dispatch=2)
    st = maml.init_state(scfg)
    h, w, c = scfg.im_shape
    n, t = scfg.num_classes_per_set, scfg.num_target_samples
    rng = np.random.RandomState(29)
    store = rng.randint(0, 256, (32, h, w, c)).astype(np.uint8)
    engines = {
        "f32": ServingEngine(scfg, st, shots_buckets=(1,),
                             strict_retrace=True, cache_size=8),
        "uint8": ServingEngine(scfg, st, shots_buckets=(1,),
                               strict_retrace=True, ingest="uint8"),
        "index": ServingEngine(scfg, st, shots_buckets=(1,),
                               strict_retrace=True, ingest="index",
                               store=store),
    }
    for eng in engines.values():
        eng.warmup()
    from howtotrainyourmamlpytorch_tpu.serving import IndexRequest

    f32_pool = [_request(scfg, rng, tenant_id=f"p{i}") for i in range(3)]
    for round_i in range(3):
        for size in (1, 2):
            engines["f32"].serve_group(
                [f32_pool[(round_i + j) % 3] for j in range(size)][:size]
                if round_i else
                [_request(scfg, rng) for _ in range(size)]
            )
            engines["uint8"].serve_group(
                [_uint8_request(scfg, rng) for _ in range(size)]
            )
            engines["index"].serve_group([
                IndexRequest(
                    support_idx=rng.randint(0, 32, (n, 1)).astype(np.int32),
                    query_idx=rng.randint(0, 32, (n, t)).astype(np.int32),
                )
                for _ in range(size)
            ])
    for name, eng in engines.items():
        assert eng.retrace_detector.retrace_count == 0, name


# -- AOT export artifacts (PR 13 tentpole) -----------------------------------


def test_export_artifacts_zero_compile_warmup_bit_exact(cfg, state,
                                                        tmp_path):
    """The export round trip: a first warmup compiles-then-saves, a
    FRESH engine's warmup deserializes the artifacts with ZERO XLA
    compilations (the compile-count assertion) and measurably faster,
    serves bit-identically to the compiled engine, and still passes the
    strict zero-retrace gate."""
    scfg = make_serving_cfg(serving_bucket_ladder=[1],
                            serving_max_tenants_per_dispatch=1)
    st = maml.init_state(scfg)
    root = str(tmp_path / "artifacts")
    eng1 = ServingEngine(scfg, st, shots_buckets=(1,),
                         strict_retrace=True)
    s1 = eng1.warmup(artifact_dir=root)
    assert eng1.warmup_stats["mode"] == "compile"
    assert eng1.warmup_stats["xla_compiles"] >= 1
    eng2 = ServingEngine(scfg, st, shots_buckets=(1,),
                         strict_retrace=True, sink=_ListSink())
    s2 = eng2.warmup(artifact_dir=root)
    assert eng2.warmup_stats["mode"] == "artifacts"
    assert eng2.warmup_stats["xla_compiles"] == 0  # the whole point
    assert s2 < s1  # deserialize beats compile
    rng = np.random.RandomState(30)
    req = _request(scfg, rng, tenant_id="x")
    dr1, dr2 = eng1.serve_group([req]), eng2.serve_group([req])
    assert np.array_equal(dr1.results[0].preds, dr2.results[0].preds)
    assert dr1.results[0].loss == dr2.results[0].loss
    assert eng2.retrace_detector.retrace_count == 0
    # the warmup telemetry record documents the artifact path
    warm = [r for r in eng2.sink.records if r.get("event") == "warmup"]
    assert len(warm) == 1 and warm[0]["mode"] == "artifacts"
    assert warm[0]["xla_compiles"] == 0
    tel.validate_record(warm[0])


def test_export_artifacts_mismatch_falls_back_to_compile(cfg, state,
                                                         tmp_path):
    """A stale/foreign artifact dir (different config fingerprint) must
    degrade to the compile path — never load a wrong program."""
    scfg = make_serving_cfg(serving_bucket_ladder=[1],
                            serving_max_tenants_per_dispatch=1)
    st = maml.init_state(scfg)
    root = str(tmp_path / "artifacts")
    eng1 = ServingEngine(scfg, st, shots_buckets=(1,), strict_retrace=True)
    eng1.warmup(artifact_dir=root)
    # a config with a different geometry fingerprints differently and
    # must not see eng1's artifacts
    other = make_serving_cfg(serving_bucket_ladder=[1],
                             serving_max_tenants_per_dispatch=1,
                             num_target_samples=3)
    eng2 = ServingEngine(other, maml.init_state(other), shots_buckets=(1,),
                         strict_retrace=True)
    eng2.warmup(artifact_dir=root)
    assert eng2.warmup_stats["mode"] == "compile"
    # and the fallback SAVED its own artifacts: a third engine loads
    eng3 = ServingEngine(other, maml.init_state(other), shots_buckets=(1,),
                         strict_retrace=True)
    eng3.warmup(artifact_dir=root)
    assert eng3.warmup_stats["mode"] == "artifacts"


def test_rollup_carries_fast_path_fields(cfg, cache_engine):
    """The v9 rollup surface: ingest, h2d_bytes_per_dispatch and
    cache_hit_rate ride the rollup (and validate against the schema)."""
    rollup = cache_engine.rollup()
    assert rollup["ingest"] == "f32"
    assert rollup["h2d_bytes_per_dispatch"] > 0
    assert 0.0 <= rollup["cache_hit_rate"] <= 1.0
    rec = cache_engine.sink.records[-1]
    assert rec["event"] == "rollup"
    tel.validate_record(rec)


# -- serve-bench (compile-heavy e2e: slow lane) ------------------------------


@pytest.mark.slow
def test_serve_bench_fast_end_to_end(tmp_path, capsys):
    """`cli serve-bench --fast` exits 0, prints one parsable JSON line
    with the latency/throughput metrics, and writes a schema-valid
    serving telemetry log the inspect CLI renders."""
    from howtotrainyourmamlpytorch_tpu.serving import bench as serve_bench
    from howtotrainyourmamlpytorch_tpu.tools import telemetry_cli

    log = tmp_path / "serving.jsonl"
    rc = serve_bench.main(
        ["--fast", "--requests", "7", "--telemetry", str(log),
         "--trace", "--metrics-port", "0"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["metric"] == "serving_adaptation_latency_ms"
    assert rec["adaptation_latency_ms_p50"] > 0
    assert rec["adaptation_latency_ms_p95"] >= rec["adaptation_latency_ms_p50"]
    assert rec["tenants_per_sec"] > 0
    assert rec["tenants"] == 7
    assert rec["retraces"] == 0
    # the v10 latency decomposition rides the line: dispatch + sync == the
    # end-to-end adapt latency (same clock, same dispatches)
    assert rec["dispatch_ms_p50"] > 0 and rec["sync_ms_p50"] >= 0
    assert rec["batch_ms_mean"] >= 0 and rec["queue_ms_p50"] == 0.0
    assert rec["metrics_port"] > 0 and rec["traced"] is True
    # the log validates: per-dispatch records + warmup + rollup + spans
    recs = list(tel.iter_records(str(log)))
    tel.validate_file(str(log))
    spans = [r for r in recs if r["kind"] == "span"]
    assert spans and {"assemble", "dispatch", "sync"} <= {
        s["name"] for s in spans
    }
    assert telemetry_cli.main(["summary", str(log)]) == 0
    summary_out = capsys.readouterr().out
    assert "serving:" in summary_out
    assert "serving[adapt/b" in summary_out  # the per-bucket breakdown
    # `cli trace` renders the spans into a loadable Chrome trace
    from howtotrainyourmamlpytorch_tpu.tools import trace_cli

    assert trace_cli.main([str(log)]) == 0
    trace = json.loads((tmp_path / "serving.trace.json").read_text())
    assert any(e.get("ph") == "X" for e in trace["traceEvents"])


# -- schema v10: latency decomposition, spans, watchdog, metrics -------------


def test_dispatch_latency_decomposition_adds_up(cfg, engine):
    """The acceptance identity: queue + batch + dispatch + sync accounts
    for the end-to-end latency. adapt_ms == dispatch_ms + sync_ms by
    construction (the same perf_counter stamps), and the summed stages
    cover the serve_group wall time up to the cache-lookup/realign
    slack."""
    import time as _time

    rng = np.random.RandomState(5)
    reqs = [_request(cfg, rng) for _ in range(2)]
    t0 = _time.perf_counter()
    dr = engine.serve_group(reqs)
    wall_ms = (_time.perf_counter() - t0) * 1e3
    assert dr.adapt_ms == pytest.approx(
        dr.dispatch_ms + dr.sync_ms, rel=0.01, abs=0.05
    )
    parts = dr.queue_ms + dr.batch_ms + dr.dispatch_ms + dr.sync_ms
    # stages never exceed the wall and cover most of it (realign +
    # result-object assembly is the only unattributed work)
    assert parts <= wall_ms + 0.5
    assert parts >= 0.4 * wall_ms
    # the dispatch record carries the same decomposition, schema-valid
    rec = engine.sink.records[-1]
    assert rec["kind"] == "serving" and rec["event"] == "dispatch"
    tel.validate_record(rec)
    assert rec["adapt_ms"] == pytest.approx(
        rec["dispatch_ms"] + rec["sync_ms"], rel=0.02, abs=0.1
    )
    assert rec["batch_ms"] >= 0
    # and the rollup mirrors it
    rollup = engine.rollup()
    assert rollup["dispatch_ms_p50"] > 0
    assert rollup["sync_ms_p50"] >= 0
    assert rollup["batch_ms_mean"] >= 0


def test_tracing_off_emits_no_spans_same_dispatch_count(cfg, engine):
    """Tracing off is free: no span records, identical dispatch count
    (the compiled programs never see the tracer), zero retraces."""
    from howtotrainyourmamlpytorch_tpu.telemetry.sinks import make_record
    from howtotrainyourmamlpytorch_tpu.telemetry.tracing import Tracer

    rng = np.random.RandomState(11)
    groups = [[_request(cfg, rng)], [_request(cfg, rng) for _ in range(2)]]

    def dispatch_count():
        return sum(
            1 for r in engine.sink.records
            if r.get("kind") == "serving" and r.get("event") == "dispatch"
        )

    base = dispatch_count()
    retraces0 = engine.retrace_detector.retrace_count
    for g in groups:
        engine.serve_group(g)
    off_dispatches = dispatch_count() - base
    spans = []
    engine.tracer = Tracer(
        emit=lambda **f: spans.append(make_record("span", **f))
    )
    try:
        for g in groups:
            engine.serve_group(g)
    finally:
        from howtotrainyourmamlpytorch_tpu.telemetry.tracing import (
            NULL_TRACER,
        )

        engine.tracer = NULL_TRACER
    on_dispatches = dispatch_count() - base - off_dispatches
    assert off_dispatches == on_dispatches == len(groups)
    assert engine.retrace_detector.retrace_count == retraces0
    # tracing ON emitted stage spans; the engine's JSONL sink saw NONE
    # of the off half's dispatches produce span records
    assert spans and {"assemble", "dispatch", "sync"} <= {
        s["name"] for s in spans
    }
    assert not any(r.get("kind") == "span" for r in engine.sink.records)


def test_microbatcher_spans_nest_request_to_sync(cfg, engine):
    """One submitted request's span tree crosses threads: queue ends
    before the dispatch, and the engine's assemble/dispatch/sync spans
    (worker thread) nest under the request root (submit thread)."""
    from howtotrainyourmamlpytorch_tpu.telemetry.sinks import make_record
    from howtotrainyourmamlpytorch_tpu.telemetry.tracing import (
        NULL_TRACER,
        Tracer,
    )

    spans = []
    tracer = Tracer(
        emit=lambda **f: spans.append(make_record("span", **f))
    )
    engine.tracer = tracer
    try:
        batcher = MicroBatcher(engine, max_wait_ms=0.0)
        rng = np.random.RandomState(13)
        handle = batcher.submit(_request(cfg, rng, tenant_id="t-span"))
        result = handle.get(timeout=60)
        batcher.close()
    finally:
        engine.tracer = NULL_TRACER
    assert result.preds is not None
    for rec in spans:
        tel.validate_record(rec)
    by_name = {}
    for rec in spans:
        by_name.setdefault(rec["name"], []).append(rec)
    for name in ("request", "queue", "assemble", "dispatch", "sync"):
        assert name in by_name, f"missing {name!r} span"
    request = by_name["request"][0]
    assert request["attrs"]["request_id"].startswith(tracer.trace_id)
    assert request["attrs"]["tenant_id"] == "t-span"
    assert request["attrs"]["outcome"] == "served"
    # the causal tree: queue AND the engine stages all parent on the root
    root_id = request["span_id"]
    for name in ("queue", "dispatch", "sync"):
        assert by_name[name][0]["parent_id"] == root_id, name
    # queue closed before the dispatch opened (grouping happened between)
    q = by_name["queue"][0]
    d = by_name["dispatch"][0]
    assert q["start_ms"] + q["dur_ms"] <= d["start_ms"] + 0.5
    # worker-thread spans carry the worker's thread name
    assert d["tid"] == "serving-batcher"
    assert request["tid"] != "serving-batcher"


def test_engine_beats_watchdog_per_dispatch(cfg, engine):
    beats = []

    class _Dog:
        def beat(self, stage):
            beats.append(stage)

    engine.watchdog = _Dog()
    try:
        rng = np.random.RandomState(3)
        engine.serve_group([_request(cfg, rng)])
    finally:
        engine.watchdog = None
    assert beats == ["serve_step[i=f32,b=1,s=1]"]


def test_serving_watchdog_stall_record_and_incident(cfg, engine, tmp_path):
    """A wedged serving dispatch (simulated: beats stop) produces one
    schema-valid watchdog_stall record naming the dispatch site plus a
    flight-recorder incident directory."""
    import os as _os
    import time as _time

    from howtotrainyourmamlpytorch_tpu.serving.engine import (
        attach_serving_watchdog,
    )
    from howtotrainyourmamlpytorch_tpu.telemetry import FlightRecorder

    sink = _ListSink()
    recorder = FlightRecorder(8, str(tmp_path / "incidents"))
    recorder.note_event("dispatch", site="serve_step[i=f32,b=1,s=1]")
    dog = attach_serving_watchdog(
        engine, timeout_s=0.15, sink=sink, recorder=recorder
    )
    try:
        assert engine.watchdog is dog
        dog.beat("serve_step[i=f32,b=2,s=1]")  # the wedged dispatch
        deadline = _time.perf_counter() + 5.0
        while not sink.records and _time.perf_counter() < deadline:
            _time.sleep(0.05)
    finally:
        dog.stop()
        engine.watchdog = None
    stalls = [r for r in sink.records if r["kind"] == "watchdog_stall"]
    assert len(stalls) == 1  # one loud diagnostic, not a flood
    tel.validate_record(stalls[0])
    assert stalls[0]["stage"] == "serve_step[i=f32,b=2,s=1]"
    assert stalls[0]["stacks"]
    assert stalls[0]["recorder_tail"]  # the ring context rode along
    incidents = [r for r in sink.records if r["kind"] == "incident"]
    assert incidents and _os.path.isdir(incidents[0]["path"])
    assert incidents[0]["reason"] == "watchdog_stall"


def test_serving_metrics_endpoint_consistent_with_records(cfg, engine):
    """ServingMetrics teed off the live record stream: counters and
    histogram totals match the records, and the endpoint serves
    parseable Prometheus text while the engine dispatches."""
    import urllib.request

    from howtotrainyourmamlpytorch_tpu.serving.metrics import (
        FanoutSink,
        MetricsServer,
        ServingMetrics,
        parse_prometheus_text,
    )

    capture = _ListSink()
    metrics = ServingMetrics()
    old_sink = engine.sink
    engine.sink = FanoutSink(capture, metrics)
    server = MetricsServer(metrics, port=0)
    try:
        rng = np.random.RandomState(17)
        engine.serve_group([_request(cfg, rng)])
        engine.serve_group([_request(cfg, rng) for _ in range(2)])
        with urllib.request.urlopen(server.url, timeout=10) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            text = resp.read().decode()
    finally:
        server.close()
        engine.sink = old_sink
    series = parse_prometheus_text(text)  # raises on malformed lines
    dispatches = [
        r for r in capture.records
        if r["kind"] == "serving" and r["event"] == "dispatch"
    ]
    assert series["serving_requests_total"][""] == sum(
        r["tenants"] for r in dispatches
    ) == 3
    assert series["serving_dispatches_total"]['program="adapt"'] == 2
    assert series["serving_h2d_bytes_total"][""] == sum(
        r["ingest_bytes"] for r in dispatches
    )
    assert series["serving_adapt_latency_ms_count"][""] == 2
    assert series["serving_adapt_latency_ms_sum"][""] == pytest.approx(
        sum(r["adapt_ms"] for r in dispatches), rel=0.01
    )
    # histogram buckets are cumulative and end at the count
    buckets = series["serving_adapt_latency_ms_bucket"]
    values = [v for _, v in sorted(buckets.items())]
    assert buckets['le="+Inf"'] == 2
    assert all(v <= 2 for v in values)
    assert series["serving_cache_hits_total"][""] == 0


def test_metrics_queue_depth_gauge_via_batcher(cfg, engine):
    from howtotrainyourmamlpytorch_tpu.serving.metrics import (
        ServingMetrics,
    )

    metrics = ServingMetrics()
    batcher = MicroBatcher(engine, max_wait_ms=0.0, metrics=metrics)
    rng = np.random.RandomState(19)
    handle = batcher.submit(_request(cfg, rng))
    handle.get(timeout=60)
    batcher.close()
    # the gauge saw the enqueue (depth 1) and renders in the exposition
    assert "serving_queue_depth" in metrics.render()


def test_engine_polls_ondemand_profiler_per_dispatch(cfg, engine, tmp_path):
    """The serving half of on-demand profiling: a trigger file captures
    the next N dispatches (warmup excluded by construction — the engine
    only polls outside warmup)."""

    class _FakeProfiler:
        def __init__(self):
            self.calls = []

        def start_trace(self, d):
            self.calls.append(("start", d))

        def stop_trace(self):
            self.calls.append(("stop",))

    from howtotrainyourmamlpytorch_tpu.utils.profiling import (
        OnDemandProfiler,
    )

    fake = _FakeProfiler()
    prof = OnDemandProfiler(
        str(tmp_path / "PROFILE_REQUEST"), str(tmp_path / "traces"),
        profiler_module=fake,
    )
    engine.profiler = prof
    try:
        rng = np.random.RandomState(23)
        engine.serve_group([_request(cfg, rng)])  # idle: no trigger yet
        assert fake.calls == []
        (tmp_path / "PROFILE_REQUEST").write_text("2")
        engine.serve_group([_request(cfg, rng)])  # starts the window
        assert prof.active
        engine.serve_group([_request(cfg, rng)])  # captured dispatch 2
        engine.serve_group([_request(cfg, rng)])  # window over: stopped
        assert not prof.active
    finally:
        engine.profiler = None
    assert [c[0] for c in fake.calls] == ["start", "stop"]


# -- schema v11: multi-replica pool, cache-affinity router, rollover ---------


@pytest.fixture(scope="module")
def pool_cfg():
    """The pool protocol's config: same geometry as `cfg` (so `state`
    is reusable), smaller program ladder (the pool compiles it once PER
    REPLICA)."""
    return make_serving_cfg(
        serving_bucket_ladder=[1, 2], serving_max_tenants_per_dispatch=2
    )


@pytest.fixture(scope="module")
def pool(pool_cfg, state):
    """A warmed 2-replica shared-nothing pool over the module snapshot
    (the conftest forces 8 virtual CPU devices, so each replica owns a
    disjoint 4-device slice). Cache ON: the affinity tests need the
    adapted-params LRU live."""
    ps = ReplicaSet(
        pool_cfg, state, n_replicas=2, devices=jax.devices()[:2],
        shots_buckets=(1,), sink=_ListSink(), strict_retrace=True,
        cache_size=32,
    )
    ps.warmup()
    yield ps
    ps.close()


@pytest.fixture(scope="module")
def single_engine(pool_cfg, state):
    """The single-engine comparator for the pool bit-exactness contract:
    same config, same snapshot, no pool."""
    eng = ServingEngine(
        pool_cfg, state, shots_buckets=(1,), strict_retrace=True,
    )
    eng.warmup()
    return eng


def _request_homed(cfg, target, n_replicas, rng, shots=1, tries=256):
    """A request whose affinity HOME is `target` (draw until the stable
    fingerprint lands there — p=1/n per draw, so 256 tries is ~never
    exhausted)."""
    for _ in range(tries):
        req = _request(cfg, rng, shots=shots)
        if home_replica(request_fingerprint(req), n_replicas) == target:
            return req
    raise AssertionError("could not draw a request homed on "
                         f"replica {target}")


def test_partition_devices_disjoint_slices():
    devices = [f"d{i}" for i in range(8)]
    slices = partition_devices(devices, 3)
    assert [len(s) for s in slices] == [2, 2, 2]  # remainder unassigned
    flat = [d for s in slices for d in s]
    assert len(flat) == len(set(flat))  # disjoint
    assert partition_devices(devices, 1) == [devices]
    with pytest.raises(ValueError, match=">= 1"):
        partition_devices(devices, 0)
    with pytest.raises(ValueError, match="disjoint"):
        partition_devices(devices[:2], 3)


def test_affinity_fingerprint_stable_across_process_restarts(tmp_path):
    """The router's home assignment must survive a front-tier restart:
    the fingerprint is content-hash-based, NEVER the per-process-seeded
    builtin hash(). Two fresh interpreters with different
    PYTHONHASHSEEDs must agree with this process bit-for-bit."""
    import subprocess
    import sys as _sys

    script = (
        "import numpy as np\n"
        "from howtotrainyourmamlpytorch_tpu.serving.router import (\n"
        "    home_replica, request_fingerprint)\n"
        "from howtotrainyourmamlpytorch_tpu.serving.batcher import (\n"
        "    AdaptRequest, IndexRequest)\n"
        "rng = np.random.RandomState(123)\n"
        "req = AdaptRequest(\n"
        "    support_x=rng.randn(3, 1, 10, 10, 1).astype(np.float32),\n"
        "    support_y=np.tile(\n"
        "        np.arange(3, dtype=np.int32)[:, None], (1, 1)),\n"
        "    query_x=rng.randn(3, 2, 10, 10, 1).astype(np.float32),\n"
        "    query_y=None)\n"
        "idx = IndexRequest(\n"
        "    support_idx=np.arange(3, dtype=np.int64)[:, None],\n"
        "    query_idx=np.arange(6, dtype=np.int64).reshape(3, 2))\n"
        "print(request_fingerprint(req), home_replica("
        "request_fingerprint(req), 5))\n"
        "print(request_fingerprint(idx), home_replica("
        "request_fingerprint(idx), 5))\n"
    )
    outs = []
    for seed in ("0", "424242"):
        env = dict(os.environ, PYTHONHASHSEED=seed, JAX_PLATFORMS="cpu")
        outs.append(subprocess.run(
            [_sys.executable, "-c", script], env=env, text=True,
            capture_output=True, check=True, timeout=120,
        ).stdout)
    assert outs[0] == outs[1]
    # ... and with THIS process (different interpreter lifetime again)
    from howtotrainyourmamlpytorch_tpu.serving.batcher import IndexRequest

    rng = np.random.RandomState(123)
    req = AdaptRequest(
        support_x=rng.randn(3, 1, 10, 10, 1).astype(np.float32),
        support_y=np.tile(np.arange(3, dtype=np.int32)[:, None], (1, 1)),
        query_x=rng.randn(3, 2, 10, 10, 1).astype(np.float32),
        query_y=None,
    )
    line0 = outs[0].splitlines()[0].split()
    assert line0[0] == request_fingerprint(req)
    assert int(line0[1]) == home_replica(request_fingerprint(req), 5)
    # the fingerprint deliberately EXCLUDES the snapshot salt: a
    # checkpoint rollover must not reshuffle homes (the adapted-cache
    # key embeds the snapshot hash separately and invalidates alone)
    idx = IndexRequest(
        support_idx=np.arange(3, dtype=np.int64)[:, None],
        query_idx=np.arange(6, dtype=np.int64).reshape(3, 2),
    )
    assert request_fingerprint(idx) == outs[0].splitlines()[1].split()[0]


class _StubReplica:
    """Router-unit-test replica: health/queue knobs, no engine."""

    def __init__(self, replica_id, depth=0, healthy=True):
        self.replica_id = replica_id
        self._depth = depth
        self.healthy = healthy
        self.tripped = False
        self.trip_cause = None
        self.submitted = []

    def queue_depth(self):
        return self._depth

    def trip(self, cause=None):
        if self.tripped:
            return False
        self.tripped = True
        self.healthy = False
        return True

    def submit(self, request):
        self.submitted.append(request)
        return f"pending-{self.replica_id}"


def test_router_affinity_spillover_and_rehoming(cfg):
    """The three routing regimes, isolated on stub replicas: pure
    affinity when the home is healthy+shallow; least-loaded spillover
    when the home's backlog reaches spill_depth; deterministic ring
    re-homing when the home is down."""
    rng = np.random.RandomState(31)
    replicas = [_StubReplica(i) for i in range(3)]
    router = ReplicaRouter(replicas, spill_depth=4)
    req = _request_homed(cfg, 1, 3, rng)

    # affinity: lands on its home
    assert router.route(req) is replicas[1]
    assert router.stats()["routed_affinity"] == 1

    # spillover: saturated home -> least-loaded healthy replica
    replicas[1]._depth = 4
    replicas[0]._depth = 2
    replicas[2]._depth = 1
    assert router.route(req) is replicas[2]
    assert router.stats()["routed_spill"] == 1

    # a saturated home that is ALSO the least loaded keeps its traffic
    replicas[1]._depth = 4
    replicas[0]._depth = replicas[2]._depth = 9
    assert router.route(req) is replicas[1]

    # re-homing: a dead home walks the ring DETERMINISTICALLY (1 -> 2),
    # and the sweep trips the dead replica exactly once
    replicas[0]._depth = replicas[1]._depth = replicas[2]._depth = 0
    replicas[1].healthy = False
    assert router.route(req) is replicas[2]
    assert replicas[1].tripped
    stats = router.stats()
    assert stats["routed_rehomed"] == 1 and stats["trips"] == 1
    assert router.route(req) is replicas[2]  # stable fallback
    assert router.stats()["trips"] == 1  # idempotent sweep

    # the whole pool down is a routing error carrying per-replica causes
    from howtotrainyourmamlpytorch_tpu.serving.router import (
        AllReplicasUnhealthyError,
    )

    for r in replicas:
        r.healthy = False
    with pytest.raises(AllReplicasUnhealthyError):
        router.route(req)


def test_pool_bit_exact_vs_single_engine(pool_cfg, pool, single_engine):
    """The pool-level correctness contract: routing a request stream
    through the N-replica pool returns byte-identical TenantResults to
    the single comparator engine (same snapshot, same per-request
    dispatch width — width-matched, because XLA codegen is
    width-dependent)."""
    rng = np.random.RandomState(41)
    requests = [_request(cfg=pool_cfg, rng=rng) for _ in range(6)]
    router = ReplicaRouter(pool, spill_depth=10_000)
    homes = {
        home_replica(request_fingerprint(r), pool.n_replicas)
        for r in requests
    }
    assert len(homes) == 2  # the draw exercises both replicas
    for req in requests:
        pooled = router.submit(req).get(timeout=300)
        single = single_engine.serve_group([req]).results[0]
        assert np.array_equal(pooled.preds, single.preds)
        assert pooled.loss == single.loss
        assert pooled.accuracy == single.accuracy
    stats = router.stats()
    assert stats["routed_total"] == 6
    assert stats["routed_affinity"] == 6  # nothing spilled or re-homed


def test_affinity_preserves_cache_hits_across_pool(pool_cfg, pool):
    """Scale-out must not dilute the adapted-params cache: a repeat
    tenant hashes to the SAME home replica, whose LRU still holds its
    adapted params — every repeat is a hit, exactly as on one engine."""
    rng = np.random.RandomState(43)
    requests = [_request(cfg=pool_cfg, rng=rng) for _ in range(4)]
    router = ReplicaRouter(pool, spill_depth=10_000)
    hits_before = {
        r.replica_id: r.engine.cache_hits for r in pool.replicas
    }
    for req in requests:  # first pass: misses populate each home's LRU
        router.submit(req).get(timeout=300)
    for req in requests:  # second pass: every repeat hits its home
        router.submit(req).get(timeout=300)
    hits = sum(
        r.engine.cache_hits - hits_before[r.replica_id]
        for r in pool.replicas
    )
    assert hits == len(requests)
    # per-replica telemetry stays attributable: pooled records carry
    # replica_id (schema v11) and validate
    recs = [
        r for r in pool.sink.records if r.get("kind") == "serving"
        and r.get("event") == "dispatch"
    ]
    assert recs and all(r["replica_id"] in (0, 1) for r in recs)
    for r in recs[-4:]:
        tel.validate_record(r)


def test_pool_rollup_aggregates_per_replica(pool):
    """The pool rollup: per-replica rollups tagged with replica_id plus
    honest aggregates (tenants summed; tenants_per_sec over the UNION
    span, never a sum of overlapping per-replica rates)."""
    ru = pool.rollup()
    assert ru["replicas"] == 2
    assert [p["replica_id"] for p in ru["per_replica"]] == [0, 1]
    assert ru["tenants"] == sum(p["tenants"] for p in ru["per_replica"])
    assert ru["dispatches"] == sum(
        p["dispatches"] for p in ru["per_replica"]
    )
    assert ru["tenants_per_sec"] > 0
    assert 0.0 <= ru["cache_hit_rate"] <= 1.0
    assert ru["retraces"] == 0


@pytest.mark.slow
def test_circuit_break_rehome_recover(pool_cfg, state):
    """The full breaker lifecycle on a real 2-replica pool: a replica
    whose engine dies is tripped on the next routing sweep (queued
    futures fail NOW with the chained root cause), its traffic re-homes
    deterministically, and a restart_replica'd replacement is picked up
    automatically — circuit-break -> re-home -> recover."""
    tiny = make_serving_cfg(
        serving_bucket_ladder=[1], serving_max_tenants_per_dispatch=1
    )
    ps = ReplicaSet(
        tiny, state, n_replicas=2, devices=jax.devices()[:2],
        shots_buckets=(1,), strict_retrace=True,
    )
    ps.warmup()
    try:
        rng = np.random.RandomState(47)
        router = ReplicaRouter(ps, spill_depth=10_000)
        victim = 0
        req_home0 = _request_homed(tiny, victim, 2, rng)
        assert router.submit(req_home0).get(timeout=300) is not None

        # kill replica 0's engine mid-service (the post-donation-crash
        # shape: the engine latches _dead with the root cause)
        boom = RuntimeError("replica 0 device fell over")

        def _explode(*a, **k):
            raise boom

        eng0 = ps.replicas[victim].engine
        eng0._programs = {key: _explode for key in eng0._programs}
        dead_pending = router.submit(_request_homed(tiny, victim, 2, rng))
        with pytest.raises(RuntimeError, match="device fell over"):
            dead_pending.get(timeout=300)
        assert not ps.replicas[victim].healthy

        # stash a queued future on the broken replica: the trip must
        # fail it immediately with the chained cause, NOT strand it
        stranded = ps.replicas[victim].batcher.submit(
            _request_homed(tiny, victim, 2, rng)
        )

        # next routed request sweeps health -> trips replica 0 ->
        # re-homes to replica 1 and SUCCEEDS
        rerouted = router.submit(_request_homed(tiny, victim, 2, rng))
        assert rerouted.get(timeout=300) is not None
        assert ps.replicas[victim].tripped
        stats = router.stats()
        assert stats["trips"] == 1 and stats["routed_rehomed"] == 1
        with pytest.raises(RuntimeError) as ei:
            stranded.get(timeout=60)
        # the breaker chains the ORIGINAL root cause through the error
        causes = []
        exc = ei.value
        while exc is not None:
            causes.append(exc)
            exc = exc.__cause__
        assert boom in causes
        # direct submits to a tripped replica are refused with the cause
        with pytest.raises(RuntimeError, match="circuit-broken"):
            ps.replicas[victim].submit(_request_homed(tiny, victim, 2, rng))

        # recover: a fresh warmed replica takes the slot and its
        # affinity traffic comes home (the router reads the live pool)
        fresh = ps.restart_replica(victim, state)
        assert fresh.healthy
        back_home = _request_homed(tiny, victim, 2, rng)
        assert router.route(back_home) is fresh
        assert router.submit(back_home).get(timeout=300) is not None
    finally:
        ps.close()


def test_batcher_close_immediate_on_never_warmed_engine(pool_cfg, state):
    """Regression (the breaker-drain fix): close() against an engine
    that never completed warmup() must NOT block on the worker join for
    the full max-wait, and must NOT dispatch the backlog (that would pay
    the whole lazy-compile bill just to tear the replica down) — the
    queued futures fail promptly instead."""
    import time as _time

    eng = ServingEngine(
        pool_cfg, state, shots_buckets=(1,), strict_retrace=False,
    )
    assert not eng.warmup_stats  # never warmed
    batcher = MicroBatcher(eng, max_wait_ms=30_000.0)
    rng = np.random.RandomState(53)
    pending = batcher.submit(_request(pool_cfg, rng))
    start = _time.perf_counter()
    batcher.close()
    elapsed = _time.perf_counter() - start
    assert elapsed < 5.0, (
        f"close() of a never-warmed engine took {elapsed:.1f}s — it must "
        "shut down immediately, not wait out max_wait/compile the ladder"
    )
    with pytest.raises(RuntimeError, match="never warmed or is dead"):
        pending.get(timeout=10)
    # drain=True still forces the old serve-the-backlog semantics on a
    # WARMED engine (the graceful pool shutdown path)
    warmed = ServingEngine(
        pool_cfg, state, shots_buckets=(1,), strict_retrace=False,
    )
    warmed.warmup()
    b2 = MicroBatcher(warmed, max_wait_ms=30_000.0)
    p2 = b2.submit(_request(pool_cfg, rng))
    b2.close(drain=True)
    assert p2.get(timeout=10) is not None


@pytest.mark.slow
def test_replica_swap_engine_zero_compile_mid_traffic(pool_cfg, state):
    """The rollover primitive: a WARMED standby swaps in under the
    dispatch lock with zero XLA compiles at swap time and zero dropped
    requests; a cold standby is refused outright."""
    ps = ReplicaSet(
        pool_cfg, state, n_replicas=1, devices=jax.devices()[:1],
        shots_buckets=(1,), strict_retrace=True,
    )
    ps.warmup()
    try:
        replica = ps.replicas[0]
        rng = np.random.RandomState(59)
        cold = ps.build_standby_engine(0, state)
        with pytest.raises(ValueError, match="warmup"):
            replica.swap_engine(cold)

        before = replica.submit(_request(pool_cfg, rng))
        standby = ps.build_standby_engine(0, state)
        standby.warmup()  # compiles HERE, off the swap path
        swap = replica.swap_engine(standby)
        after = replica.submit(_request(pool_cfg, rng))
        assert swap["xla_compiles_at_swap"] == 0
        assert swap["replica_id"] == 0
        assert before.get(timeout=300) is not None
        assert after.get(timeout=300) is not None
        assert replica.engine is standby
    finally:
        ps.close()


@pytest.mark.slow
def test_refresh_daemon_rolls_pool_on_new_checkpoint(pool_cfg, state,
                                                     tmp_path):
    """The watch -> prefetch/pre-warm -> swap lifecycle end to end: the
    daemon ignores the primed snapshot, detects a NEW checkpoint
    marker, warms a standby per replica off the hot path, swaps with
    zero compiles, emits schema-v11 rollover records, and the pool
    serves the new snapshot."""
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt

    save_dir = str(tmp_path / "saved_models")
    ckpt.save_checkpoint(
        save_dir, "train_model", "latest", state, {"current_iter": 0}
    )
    sink = _ListSink()
    ps = ReplicaSet(
        pool_cfg, state, n_replicas=1, devices=jax.devices()[:1],
        shots_buckets=(1,), sink=sink, strict_retrace=True,
    )
    ps.warmup()
    try:
        daemon = RefreshDaemon(
            ps, pool_cfg, save_dir, poll_s=0.05, sink=sink
        )
        daemon.prime()
        assert daemon.poll_once() is None  # nothing new
        assert daemon.rollovers == 0

        # training writes a NEW snapshot (perturbed, so the roll is
        # observable in the served outputs)
        rolled_state = jax.tree_util.tree_map(
            lambda x: x + 0.25 if np.issubdtype(
                np.asarray(x).dtype, np.floating) else x,
            state,
        )
        ckpt.save_checkpoint(
            save_dir, "train_model", "latest", rolled_state,
            {"current_iter": 9},
        )
        stats = daemon.poll_once()
        assert stats is not None and len(stats) == 1
        assert stats[0]["xla_compiles_at_swap"] == 0
        assert stats[0]["old_iter"] == 0 and stats[0]["new_iter"] == 9
        assert daemon.rollovers == 1 and daemon.last_error is None
        assert daemon.poll_once() is None  # idempotent until the next

        rollover_recs = [
            r for r in sink.records
            if r.get("kind") == "serving" and r.get("event") == "rollover"
        ]
        assert len(rollover_recs) == 1
        tel.validate_record(rollover_recs[0])
        assert rollover_recs[0]["new_iter"] == 9

        # the pool now serves the ROLLED snapshot: compare against a
        # fresh engine over rolled_state (width-matched single dispatch)
        rng = np.random.RandomState(61)
        req = _request(pool_cfg, rng)
        served = ps.replicas[0].submit(req).get(timeout=300)
        cmp_eng = ServingEngine(
            pool_cfg, rolled_state, shots_buckets=(1,),
            strict_retrace=False,
        )
        cmp_eng.warmup()
        expect = cmp_eng.serve_group([req]).results[0]
        assert np.array_equal(served.preds, expect.preds)
    finally:
        ps.close()


def test_pool_config_validation():
    """The scale-out knobs validate like every serving int/float."""
    make_serving_cfg(serving_replicas=2, serving_router_spill_depth=3,
                     serving_rollover_poll_s=0.5)
    coerced = make_serving_cfg(serving_replicas=2.0)
    assert coerced.serving_replicas == 2  # JSON round-trip coercion
    with pytest.raises(ValueError, match="serving_replicas"):
        make_serving_cfg(serving_replicas=0)
    with pytest.raises(ValueError, match="serving_router_spill_depth"):
        make_serving_cfg(serving_router_spill_depth=0)
    with pytest.raises(ValueError, match="serving_rollover_poll_s"):
        make_serving_cfg(serving_rollover_poll_s=0.0)
    with pytest.raises(ValueError, match="spill_depth"):
        ReplicaRouter([_StubReplica(0)], spill_depth=0)


def test_metrics_per_replica_labels_and_rollovers():
    """Schema v11 metrics: pooled records keep one series per replica
    label, unlabelled single-engine records render exactly as before,
    and rollover events count into serving_rollovers_total — all
    through the real exposition parser."""
    from howtotrainyourmamlpytorch_tpu.serving.metrics import (
        ServingMetrics,
        parse_prometheus_text,
    )

    metrics = ServingMetrics()
    base = dict(kind="serving", event="dispatch", program="adapt",
                adapt_ms=2.0, queue_ms=0.1, ingest_bytes=100,
                cache_hits=1)
    metrics.write(dict(base, tenants=3, replica_id=0))
    metrics.write(dict(base, tenants=2, replica_id=1))
    metrics.write(dict(base, tenants=4))  # single-engine: unlabelled
    metrics.write({"kind": "serving", "event": "rollover",
                   "replica_id": 1})
    metrics.observe_queue_depth(5, replica=0)
    series = parse_prometheus_text(metrics.render())
    req = series["serving_requests_total"]
    assert req['replica="0"'] == 3 and req['replica="1"'] == 2
    assert req[""] == 4
    disp = series["serving_dispatches_total"]
    assert disp['program="adapt",replica="0"'] == 1
    assert disp['program="adapt"'] == 1
    assert series["serving_rollovers_total"]['replica="1"'] == 1
    assert series["serving_queue_depth"]['replica="0"'] == 5
    assert series["serving_cache_hits_total"]['replica="0"'] == 1


def test_healthz_pool_readiness_gates_503(pool):
    """/healthz with a pool readiness probe: 503 (with per-replica
    detail) until EVERY replica is ready, 200 after; the readiness-less
    single-engine server keeps its unconditional 200."""
    import urllib.error
    import urllib.request

    from howtotrainyourmamlpytorch_tpu.serving.metrics import (
        MetricsServer,
        ServingMetrics,
    )

    states = {"0": True, "1": False}
    server = MetricsServer(
        ServingMetrics(), port=0, readiness=lambda: states
    )
    try:
        url = f"http://{server.host}:{server.port}/healthz"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url, timeout=10)
        assert ei.value.code == 503
        body = ei.value.read().decode()
        assert "replica 1: not-ready" in body
        assert "replica 0: ready" in body

        states["1"] = True
        with urllib.request.urlopen(url, timeout=10) as resp:
            assert resp.status == 200
            assert resp.read().decode().startswith("ok")

        # the REAL pool's readiness surface reports every replica warm
        assert pool.readiness() == {"0": True, "1": True}
    finally:
        server.close()

    plain = MetricsServer(ServingMetrics(), port=0)
    try:
        with urllib.request.urlopen(
            f"http://{plain.host}:{plain.port}/healthz", timeout=10
        ) as resp:
            assert resp.status == 200
    finally:
        plain.close()


@pytest.mark.slow
def test_serve_bench_replicas_pool_end_to_end(tmp_path, capsys):
    """`cli serve-bench --fast --replicas 2`: the pool line carries the
    aggregate + per-replica + router surfaces with zero drops, the
    telemetry log is schema-valid with replica-tagged records, and the
    inspect summary renders the per-replica breakdown."""
    from howtotrainyourmamlpytorch_tpu.serving import bench as serve_bench
    from howtotrainyourmamlpytorch_tpu.tools import telemetry_cli

    log = tmp_path / "pool.jsonl"
    rc = serve_bench.main(
        ["--fast", "--requests", "8", "--replicas", "2",
         "--repeat-tenant-fraction", "0.5", "--emulate-device-ms", "2",
         "--telemetry", str(log), "--metrics-port", "0"]
    )
    out = capsys.readouterr().out
    assert rc == 0
    rec = json.loads(out.strip().splitlines()[-1])
    assert rec["replicas"] == 2
    assert rec["tenants"] == 8 and rec["dropped_requests"] == 0
    assert rec["retraces"] == 0
    assert rec["tenants_per_sec"] > 0
    assert len(rec["per_replica"]) == 2
    assert rec["router"]["routed_total"] == 8
    assert rec["router"]["routed_spill"] == 0  # bench default: no spill
    assert rec["cache_hit_rate"] is not None
    assert rec["emulate_device_ms"] == 2.0
    tel.validate_file(str(log))
    tagged = [
        r for r in tel.iter_records(str(log))
        if r["kind"] == "serving" and r.get("event") == "dispatch"
    ]
    assert tagged and all("replica_id" in r for r in tagged)
    assert telemetry_cli.main(["summary", str(log)]) == 0
    summary_out = capsys.readouterr().out
    assert "serving[replica 0]:" in summary_out
    assert "2 replica(s)" in summary_out


def test_router_skips_cold_replica_without_tripping(cfg):
    """A merely not-yet-warmed replica is UNHEALTHY for routing but not
    BROKEN: the sweep must skip it (it becomes routable when warmup
    completes), never destructively trip it — tripping fails its
    backlog and closes its batcher permanently."""
    rng = np.random.RandomState(67)
    replicas = [_StubReplica(i) for i in range(2)]
    replicas[0].healthy = False   # cold: warmup still running
    replicas[0].broken = False
    replicas[1].broken = False
    router = ReplicaRouter(replicas, spill_depth=4)
    req = _request_homed(cfg, 0, 2, rng)
    assert router.route(req) is replicas[1]  # re-homed, not tripped
    assert not replicas[0].tripped
    assert router.stats()["trips"] == 0
    replicas[0].healthy = True               # warmup completed
    assert router.route(req) is replicas[0]  # traffic comes home
    # a BROKEN replica (dead engine/worker) is tripped as before
    replicas[0].healthy = False
    replicas[0].broken = True
    assert router.route(req) is replicas[1]
    assert replicas[0].tripped and router.stats()["trips"] == 1


def test_refresh_marker_peek_is_read_only(pool_cfg, state, tmp_path):
    """The daemon polls a LIVE training run's checkpoint dir: its
    marker peek must never perform the `.old` recovery rename (that
    race can crash the trainer's own save mid-swap) — same read-only
    contract as load_servable_snapshot."""
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt

    save_dir = str(tmp_path / "saved_models")
    ckpt.save_checkpoint(
        save_dir, "train_model", "latest", state, {"current_iter": 4}
    )
    path = os.path.join(save_dir, "train_model_latest")
    os.rename(path, path + ".old")  # trainer killed between renames
    daemon = RefreshDaemon(None, pool_cfg, save_dir)
    assert daemon.current_marker() == 4  # read FROM the .old sibling
    assert os.path.isdir(path + ".old") and not os.path.isdir(path)


@pytest.mark.slow
def test_refresh_partial_failure_resumes_without_double_swap(
        state, tmp_path):
    """A mid-pool rollover failure (replica 1's standby build dies
    after replica 0 already swapped) must resume at the FAILED replica
    on the next poll — never re-roll, or double-count rollover records
    for, the replicas that already swapped onto the target marker."""
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt

    tiny = make_serving_cfg(
        serving_bucket_ladder=[1], serving_max_tenants_per_dispatch=1
    )
    save_dir = str(tmp_path / "saved_models")
    ckpt.save_checkpoint(
        save_dir, "train_model", "latest", state, {"current_iter": 0}
    )
    sink = _ListSink()
    ps = ReplicaSet(
        tiny, state, n_replicas=2, devices=jax.devices()[:2],
        shots_buckets=(1,), sink=sink, strict_retrace=True,
    )
    ps.warmup()
    try:
        daemon = RefreshDaemon(ps, tiny, save_dir, poll_s=0.05, sink=sink)
        daemon.prime()
        ckpt.save_checkpoint(
            save_dir, "train_model", "latest", state, {"current_iter": 5}
        )
        orig_build = ps.build_standby_engine
        armed = [True]

        def flaky(rid, st, snapshot_id=None):
            if rid == 1 and armed[0]:
                armed[0] = False
                raise OSError("transient fs hiccup")
            return orig_build(rid, st, snapshot_id)

        ps.build_standby_engine = flaky
        assert daemon.poll_once() is None  # partial: latched, retried
        assert daemon.last_error is not None
        assert daemon.rollovers == 0
        stats = daemon.poll_once()  # retry resumes at replica 1 ONLY
        assert [s["replica_id"] for s in stats] == [1]
        assert daemon.rollovers == 1 and daemon.last_error is None
        rollover_recs = [
            r for r in sink.records
            if r.get("kind") == "serving" and r.get("event") == "rollover"
        ]
        assert sorted(r["replica_id"] for r in rollover_recs) == [0, 1]
    finally:
        ps.close()


# -- schema v12: SLO observability — histograms, deadlines, burn rates -------


def test_log_histogram_quantiles_merge_and_exposition():
    """The mergeable latency histogram's three contracts: quantiles
    agree with raw samples within one bucket's relative error
    (growth - 1), pool merge is EXACT bucket-by-bucket addition, and
    the rendered Prometheus exposition passes the parser's histogram
    validation (cumulative buckets, +Inf == _count)."""
    from howtotrainyourmamlpytorch_tpu.serving.metrics import (
        LOG_HISTOGRAM_GROWTH,
        LogHistogram,
        parse_prometheus_text,
    )

    rng = np.random.RandomState(101)
    samples = np.exp(rng.randn(4000) * 1.5 + 1.0)  # lognormal ms
    h = LogHistogram()
    for s in samples:
        h.observe(float(s))
    rel = LOG_HISTOGRAM_GROWTH - 1.0
    for q in (0.5, 0.95, 0.99):
        exact = float(np.percentile(samples, q * 100))
        est = h.quantile(q)
        assert abs(est - exact) <= rel * exact + 1e-9, (
            f"q={q}: histogram {est} vs raw {exact} beyond one bucket"
        )
    # exact merge: two disjoint halves re-merge to the full histogram
    a, b = LogHistogram(), LogHistogram()
    for s in samples[:2000]:
        a.observe(float(s))
    for s in samples[2000:]:
        b.observe(float(s))
    m = LogHistogram()
    m.merge(a)
    m.merge(b)
    assert m.counts == h.counts
    assert m.count == h.count == 4000
    assert m.min == h.min and m.max == h.max
    assert m.quantile(0.95) == h.quantile(0.95)
    # serialization round-trips through the telemetry-record form
    back = LogHistogram.from_dict(h.to_dict())
    assert back.counts == h.counts and back.count == h.count
    # mismatched ladders must refuse to merge (silent corruption)
    other = LogHistogram(low=1e-2)
    with pytest.raises(ValueError, match="ladder"):
        h.merge(other)
    # the exposition validates as a real Prometheus histogram
    text = "\n".join(h.render("t_ms", "test latency")) + "\n"
    series = parse_prometheus_text(text)
    assert series["t_ms_count"][""] == 4000
    assert series["t_ms_bucket"]['le="+Inf"'] == 4000


def test_slo_tracker_burn_rate_math():
    """Burn rate = window miss rate / error budget, windows anchored to
    the NEWEST record timestamp — so a replayed log reads the same
    numbers the live endpoint showed."""
    from howtotrainyourmamlpytorch_tpu.serving.metrics import SLOTracker

    tr = SLOTracker(target_ms=50.0, availability=0.99,
                    burn_windows_s=(60.0, 3600.0))
    t0 = 1_800_000_000.0
    for i in range(100):
        tr.write({
            "kind": "serving", "event": "deadline", "ts": t0 + i,
            "deadline_ms": 50.0, "slack_ms": 1.0,
            "missed": i == 99,  # the one miss lands in the newest second
        })
    s = tr.summary()
    assert s["requests"] == 100 and s["missed"] == 1
    # 60s window holds the last 60 events (1 miss): 1/60 / 0.01
    assert s["burn_rates"]["60"] == pytest.approx((1 / 60) / 0.01,
                                                  rel=1e-6)
    assert s["burn_rates"]["3600"] == pytest.approx(0.01 / 0.01, rel=1e-6)
    assert s["worst_burn_window_s"] == 60.0
    assert s["error_budget"] == pytest.approx(0.01)
    # non-deadline records are ignored (the tracker tees off the full
    # serving stream)
    tr.write({"kind": "serving", "event": "dispatch", "tenants": 3})
    assert tr.summary()["requests"] == 100
    with pytest.raises(ValueError, match="availability"):
        SLOTracker(target_ms=50.0, availability=1.5)
    with pytest.raises(ValueError, match="windows"):
        SLOTracker(target_ms=50.0, burn_windows_s=())


def test_micro_batcher_deadline_accounting(cfg, engine):
    """Every deadline-carrying request resolves to exactly one
    schema-valid `deadline` record with slack/miss and the stage
    attribution; requests without a deadline emit none; a non-positive
    budget is refused at submit."""
    sink = _ListSink()
    old_sink = engine.sink
    engine.sink = sink
    batcher = MicroBatcher(engine, max_wait_ms=0.0)
    rng = np.random.RandomState(67)
    try:
        req_met = _request(cfg, rng, tenant_id="t-met")
        req_met.deadline_ms = 60_000.0
        req_miss = _request(cfg, rng, tenant_id="t-miss")
        req_miss.deadline_ms = 1e-3
        met = batcher.submit(req_met)
        missed = batcher.submit(req_miss)
        plain = batcher.submit(_request(cfg, rng, tenant_id="t-plain"))
        for p in (met, missed, plain):
            assert p.get(timeout=300) is not None
        bad = _request(cfg, rng)
        bad.deadline_ms = 0.0
        with pytest.raises(ValueError, match="deadline_ms"):
            batcher.submit(bad)
    finally:
        batcher.close()
        engine.sink = old_sink
    dl = [r for r in sink.records if r.get("event") == "deadline"]
    assert len(dl) == 2  # the plain request emitted NO deadline record
    by_tenant = {r["tenant_id"]: r for r in dl}
    assert set(by_tenant) == {"t-met", "t-miss"}
    for r in dl:
        tel.validate_record(r)
        assert r["schema"] == tel.SCHEMA_VERSION
        # stage attribution: queue + route ride along with the budget
        assert r["e2e_ms"] >= r["queue_ms"] >= 0
        assert r["route_ms"] == 0.0  # no router on the direct path
        assert r["deadline_ms"] > 0
        assert r["missed"] == (r["slack_ms"] < 0)
    assert by_tenant["t-met"]["missed"] is False
    assert by_tenant["t-miss"]["missed"] is True


def _mk_deadline_request(cfg, rng, deadline_ms):
    req = _request(cfg, rng)
    req.deadline_ms = deadline_ms
    return req


def test_slo_three_way_agreement_scrape_log_cli(cfg, engine, tmp_path,
                                                capsys):
    """The acceptance contract: /metrics, the JSONL `slo`/`deadline`
    records, and `cli slo` all derive from ONE record stream and agree
    on the deadline-miss counts."""
    import urllib.request

    from howtotrainyourmamlpytorch_tpu.serving.metrics import (
        FanoutSink,
        MetricsServer,
        ServingMetrics,
        SLOTracker,
        parse_prometheus_text,
    )
    from howtotrainyourmamlpytorch_tpu.telemetry.sinks import (
        JsonlSink,
        make_record,
    )
    from howtotrainyourmamlpytorch_tpu.tools import slo_cli

    log = tmp_path / "slo.jsonl"
    jsonl = JsonlSink(str(log))
    slo = SLOTracker(target_ms=50.0)
    metrics = ServingMetrics(slo=slo)
    sink = FanoutSink(jsonl, metrics)
    old_sink = engine.sink
    engine.sink = sink
    server = MetricsServer(metrics, port=0)
    batcher = MicroBatcher(engine, max_wait_ms=0.0)
    rng = np.random.RandomState(71)
    try:
        pendings = [
            batcher.submit(_mk_deadline_request(cfg, rng, 60_000.0))
            for _ in range(3)
        ] + [
            batcher.submit(_mk_deadline_request(cfg, rng, 1e-3))
            for _ in range(2)
        ]
        for p in pendings:
            assert p.get(timeout=300) is not None
        with urllib.request.urlopen(server.url, timeout=10) as resp:
            text = resp.read().decode()
    finally:
        server.close()
        batcher.close()
        engine.sink = old_sink
    sink.write(make_record("slo", **slo.summary()))
    sink.close()
    # the live scrape (parse validates histogram exposition too)
    series = parse_prometheus_text(text)
    assert series["serving_deadline_met_total"][""] == 3
    assert series["serving_deadline_missed_total"][""] == 2
    assert series["serving_slo_error_budget"][""] == pytest.approx(0.01)
    assert any(
        k.startswith("serving_slo_burn_rate")
        for k in series
    )
    # the JSONL stream: 5 deadline records (2 missed) + the slo record,
    # all schema-valid
    tel.validate_file(str(log))
    recs = list(tel.iter_records(str(log)))
    dl = [r for r in recs if r.get("event") == "deadline"]
    assert len(dl) == 5
    assert sum(1 for r in dl if r["missed"]) == 2
    pinned = [r for r in recs if r["kind"] == "slo"]
    assert len(pinned) == 1
    assert pinned[0]["requests"] == 5 and pinned[0]["missed"] == 2
    # the offline replay agrees and exits 0 (the CI gate)
    assert slo_cli.main([str(log), "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["mismatch"] is None
    assert payload["slo"]["requests"] == 5
    assert payload["slo"]["missed"] == 2
    assert payload["slo"]["target_ms"] == 50.0
    # text mode renders the report, still exit 0
    assert slo_cli.main([str(log)]) == 0
    out = capsys.readouterr().out
    assert "SLO report" in out and "missed 2" in out


def test_slo_cli_no_deadline_data_exits_zero(tmp_path, capsys):
    """A pre-v12 log (no deadline/slo records) is an answer, not a
    crash: `cli slo` reports the absence and exits 0."""
    from howtotrainyourmamlpytorch_tpu.tools import slo_cli

    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures", "telemetry_v11_schema.jsonl"
    )
    assert slo_cli.main([fixture]) == 0
    assert "no deadline records" in capsys.readouterr().out
    assert slo_cli.main([str(tmp_path / "nope.jsonl")]) == 2


def test_inspect_summary_renders_slo_line(tmp_path, capsys):
    """`cli inspect summary` renders the v12 slo line (miss rate, worst
    burn window, per-replica breakdown) — and pre-v12 logs render
    without one, never a crash."""
    from howtotrainyourmamlpytorch_tpu.telemetry.sinks import make_record
    from howtotrainyourmamlpytorch_tpu.tools import telemetry_cli

    log = tmp_path / "slo_log.jsonl"
    with open(log, "w") as f:
        for i in range(4):
            f.write(json.dumps(make_record(
                "serving", event="deadline", deadline_ms=50.0,
                slack_ms=(-5.0 if i == 3 else 12.0), missed=(i == 3),
                e2e_ms=40.0, queue_ms=1.0, route_ms=0.1,
                replica_id=i % 2,
            )) + "\n")
        f.write(json.dumps(make_record(
            "slo", target_ms=50.0, availability=0.99, requests=4,
            missed=1, worst_burn_rate=25.0, worst_burn_window_s=60.0,
        )) + "\n")
    assert telemetry_cli.main(["summary", str(log)]) == 0
    out = capsys.readouterr().out
    assert "slo: 4 deadline(s), 1 missed" in out
    assert "worst burn 25.00 over 60s" in out
    assert "slo[replica 0]" in out and "slo[replica 1]" in out
    assert telemetry_cli.main(["summary", str(log), "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["slo"]["miss_rate"] == 0.25
    assert payload["slo"]["per_replica"]["1"]["missed"] == 1
    # pre-v12 log: no slo line, exit 0
    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures", "telemetry_v11_schema.jsonl"
    )
    assert telemetry_cli.main(["summary", fixture]) == 0
    assert "slo:" not in capsys.readouterr().out


def test_pool_watchdogs_replica_tagged_and_rewired(pool_cfg, state):
    """Satellite: per-replica watchdogs. attach_watchdogs puts one
    replica-tagged watchdog on every engine; a stall record carries the
    replica_id; _rewire_watchdog (the restart_replica hook) retires the
    old dog and arms a fresh one on the replacement engine."""
    import time as _time

    sink = _ListSink()
    ps = ReplicaSet(
        pool_cfg, state, n_replicas=2, devices=jax.devices()[:2],
        shots_buckets=(1,), sink=sink, strict_retrace=True,
    )
    # no warmup needed: the watchdog wraps the engine object, not its
    # compiled programs
    try:
        dogs = ps.attach_watchdogs(0.15, sink=sink)
        assert len(dogs) == 2
        for r in ps.replicas:
            assert r.engine.watchdog is ps._watchdogs[r.replica_id]
        # wedge replica 1 (beat once, never again)
        ps.replicas[1].engine.watchdog.beat("serve_step[i=f32,b=1,s=1]")
        deadline = _time.perf_counter() + 5.0
        while (
            not any(r.get("kind") == "watchdog_stall"
                    and r.get("replica_id") == 1
                    for r in sink.records)
            and _time.perf_counter() < deadline
        ):
            _time.sleep(0.05)
        stalls = [
            r for r in sink.records if r.get("kind") == "watchdog_stall"
            and r.get("replica_id") == 1
        ]
        assert stalls, "no replica-tagged stall record within 5s"
        tel.validate_record(stalls[0])
        # rewire: the restart path must not leave the dead engine's dog
        # running nor the fresh engine unwatched
        old_dog = ps._watchdogs[0]
        ps._rewire_watchdog(ps.replicas[0])
        assert ps._watchdogs[0] is not old_dog
        assert ps.replicas[0].engine.watchdog is ps._watchdogs[0]
    finally:
        ps.close()
    # close() stopped and cleared every watchdog
    assert not ps._watchdogs
    for r in ps.replicas:
        assert getattr(r.engine, "watchdog", None) is None


@pytest.mark.slow
def test_histograms_and_watchdog_survive_rollover(pool_cfg, state):
    """The rollover continuity contract: after a mid-run swap_engine,
    the pool histogram equals the EXACT bucket-by-bucket merge of
    everything served (pre- and post-swap — adopt_serving_history
    merged the old engine's buckets), window_dropped is honest, and
    the per-replica watchdog rides into the standby."""
    from howtotrainyourmamlpytorch_tpu.serving.metrics import LogHistogram

    sink = _ListSink()
    ps = ReplicaSet(
        pool_cfg, state, n_replicas=1, devices=jax.devices()[:1],
        shots_buckets=(1,), sink=sink, strict_retrace=True,
    )
    ps.warmup()
    try:
        ps.attach_watchdogs(600.0, sink=sink)
        dog = ps.replicas[0].engine.watchdog
        assert dog is not None
        rng = np.random.RandomState(73)
        replica = ps.replicas[0]
        for _ in range(3):
            assert replica.submit(
                _request(pool_cfg, rng)
            ).get(timeout=300) is not None
        standby = ps.build_standby_engine(0, state)
        standby.warmup()
        swap = replica.swap_engine(standby)
        assert swap["xla_compiles_at_swap"] == 0
        # the watchdog survived the swap onto the standby engine
        assert replica.engine.watchdog is dog
        for _ in range(2):
            assert replica.submit(
                _request(pool_cfg, rng)
            ).get(timeout=300) is not None
        ru = ps.rollup()
        # exact merge: rebuild the histogram from the record stream the
        # run emitted (pre-swap dispatches included) and compare
        # bucket-by-bucket
        expect = LogHistogram()
        adapt = [
            r["adapt_ms"] for r in sink.records
            if r.get("kind") == "serving" and r.get("event") == "dispatch"
        ]
        for v in adapt:
            expect.observe(float(v))
        assert len(adapt) == 5
        assert ru["adapt_ms_hist"]["counts"] == expect.to_dict()["counts"]
        assert ru["adapt_ms_hist"]["count"] == 5
        assert ru["window_dropped"] == 0  # nothing aged out: honest zero
        back = LogHistogram.from_dict(ru["adapt_ms_hist"])
        assert back.quantile(0.5) == expect.quantile(0.5)
        # the rollup record (with the histogram payload) is schema-valid
        rollup_recs = [
            r for r in sink.records
            if r.get("kind") == "serving" and r.get("event") == "rollup"
        ]
        assert rollup_recs
        for r in rollup_recs:
            tel.validate_record(r)
    finally:
        ps.close()


def test_serve_bench_openloop_arg_validation():
    """Open-loop flags are validated before any jax import: an arrival
    schedule needs --rate, --rate needs an open-loop arrival, and the
    Zipf popularity law must be normalizable."""
    from howtotrainyourmamlpytorch_tpu.serving import bench as serve_bench

    for argv in (
        ["--fast", "--arrival", "poisson"],            # no --rate
        ["--fast", "--rate", "50"],                    # closed + rate
        ["--fast", "--arrival", "poisson", "--rate", "0"],
        ["--fast", "--arrival", "poisson", "--rate", "50",
         "--deadline-ms", "0"],
        ["--fast", "--arrival", "zipf", "--rate", "50",
         "--zipf-exponent", "1.0"],
        ["--fast", "--arrival", "bursty", "--rate", "50",
         "--burst-period-s", "0"],
        ["--fast", "--arrival", "poisson", "--rate", "50",
         "--rollover"],
    ):
        with pytest.raises(SystemExit) as ei:
            serve_bench.main(argv)
        assert ei.value.code == 2, argv


def test_arrival_schedules_deterministic_and_shaped():
    """The fixed-seed arrival generators: same seed, same schedule;
    Poisson offsets are sorted with the right mean; bursty offsets land
    only in the ON half of each period; Zipf traffic skews toward the
    head tenants by reusing their exact request objects."""
    import argparse as _ap

    from howtotrainyourmamlpytorch_tpu.serving.bench import (
        _arrival_schedule,
        _zipf_requests,
    )

    def ns(**kw):
        return _ap.Namespace(**kw)

    args = ns(arrival="poisson", rate=100.0, seed=3, burst_period_s=1.0)
    a = _arrival_schedule(args, 500)
    b = _arrival_schedule(args, 500)
    assert a == b  # pure function of the seed
    assert a == sorted(a)
    # mean inter-arrival ~ 1/rate (law of large numbers, loose tol)
    assert a[-1] / 500 == pytest.approx(1 / 100.0, rel=0.25)
    burst = _arrival_schedule(
        ns(arrival="bursty", rate=100.0, seed=3, burst_period_s=0.5), 400
    )
    assert burst == sorted(burst)
    for t in burst:
        assert (t % 0.5) < 0.25 + 1e-9, (
            f"bursty arrival at {t} landed in the OFF half-period"
        )
    # zipf: the head tenant serves far more than the tail, via the SAME
    # request object (content-fingerprint cache hits)
    cfg = make_serving_cfg()
    reqs = _zipf_requests(
        cfg, [1], 200, ns(seed=3, zipf_exponent=1.5), "f32", 0
    )
    assert len(reqs) == 200
    by_id = {}
    for r in reqs:
        by_id[id(r)] = by_id.get(id(r), 0) + 1
    counts = sorted(by_id.values(), reverse=True)
    assert counts[0] >= 10 * counts[-1]  # hot head, cold tail
