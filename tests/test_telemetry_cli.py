"""The telemetry inspect/diff CLI (tools/telemetry_cli.py): every
subcommand against synthetic schema-valid logs, the config-diff and
divergence-epoch logic of ``diff``, exit codes, and the jax-free
``python -m howtotrainyourmamlpytorch_tpu.cli inspect`` dispatch path."""

import json
import subprocess
import sys

import pytest

from howtotrainyourmamlpytorch_tpu.telemetry import make_record
from howtotrainyourmamlpytorch_tpu.tools.telemetry_cli import main as cli_main


def _write_log(path, records):
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
    return str(path)


def _run_records(val_accs, config=None, loss0=2.0, anomalies=()):
    """A small schema-valid run log: run_start (+config snapshot), one
    epoch record per val accuracy (losses decaying from ``loss0``),
    dispatch/stream/device_memory records, optional anomaly records, and
    the run_end marker."""
    records = [make_record(
        "run_start", experiment_name="exp", telemetry_level="scalars",
        resume_iter=0, config=dict(config or {}),
    )]
    for e, acc in enumerate(val_accs):
        records.append(make_record(
            "epoch", epoch=e,
            scalars={
                "train_loss_mean": loss0 / (e + 1),
                "val_accuracy_mean": acc,
                "train_step_time_ms": 10.0 + e,
            },
        ))
        records.append(make_record(
            "dispatch", epoch=e,
            train_step_time_ms=10.0 + e, train_step_time_p50_ms=9.0 + e,
            train_step_time_p95_ms=12.0 + e,
        ))
        records.append(make_record(
            "stream", epoch=e, batches=8, assembly_ms_per_batch=1.5,
            stall_ms_per_batch=0.25, queue_depth_mean=3.0,
        ))
        records.append(make_record(
            "device_memory", epoch=e, store_bytes_expected=0,
            bytes_in_use=1 << 20, peak_bytes_in_use=2 << 20,
        ))
    for it, reason in anomalies:
        records.append(make_record(
            "anomaly", iter=it, reason=reason, value=1e9, threshold=10.0,
        ))
    records.append(make_record("run_end"))
    return records


def test_summary_text_and_json(tmp_path, capsys):
    log = _write_log(tmp_path / "a.jsonl", _run_records(
        [0.5, 0.8, 0.7], anomalies=[(7, "loss_spike")],
    ))
    assert cli_main(["summary", log]) == 0
    text = capsys.readouterr().out
    assert "epochs: 0..2" in text
    assert "best 0.8000 @ epoch 1" in text and "final 0.7000" in text
    assert "dispatch:" in text and "p95" in text
    assert "stream:" in text and "hbm:" in text
    assert "1 anomalies" in text

    assert cli_main(["summary", log, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["records"] == len(_run_records([0.5, 0.8, 0.7])) + 1
    assert payload["best_val_epoch"] == 1
    assert payload["anomalies"] == 1
    assert payload["clean_shutdown"] is True
    assert payload["dispatch_timing"]["train_step_time_p50_ms"] == 10.0
    assert payload["stream"]["stall_ms_per_batch"] == 0.25
    assert payload["device_memory"]["bytes_in_use"] == 1 << 20


def test_summary_flags_unclean_shutdown(tmp_path, capsys):
    recs = _run_records([0.5])[:-1]  # drop run_end: crashed / still running
    log = _write_log(tmp_path / "crashed.jsonl", recs)
    assert cli_main(["summary", log]) == 0
    assert "no run_end marker" in capsys.readouterr().out


def test_epochs_table(tmp_path, capsys):
    log = _write_log(tmp_path / "a.jsonl", _run_records([0.5, 0.75]))
    assert cli_main(["epochs", log]) == 0
    text = capsys.readouterr().out
    assert "val_accuracy_mean" in text and "0.7500" in text
    assert cli_main(["epochs", log, "--json",
                     "--column", "train_loss_mean"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["columns"] == ["train_loss_mean"]
    assert payload["epochs"]["1"]["train_loss_mean"] == 1.0


def test_anomalies_timeline(tmp_path, capsys):
    records = _run_records([0.5], anomalies=[(3, "nonfinite_grads")])
    records.append(make_record(
        "incident", iter=3, reason="halt", path="/tmp/incident_dir",
    ))
    records.append(make_record(
        "watchdog_stall", stage="train_dispatch",
        seconds_since_progress=120.0, stacks={},
    ))
    log = _write_log(tmp_path / "a.jsonl", records)
    assert cli_main(["anomalies", log]) == 0
    text = capsys.readouterr().out
    assert "nonfinite_grads" in text
    assert "halt" in text and "/tmp/incident_dir" in text
    assert "stall" in text and "train_dispatch" in text


def test_anomalies_empty(tmp_path, capsys):
    log = _write_log(tmp_path / "a.jsonl", _run_records([0.5]))
    assert cli_main(["anomalies", log]) == 0
    assert "no anomalies" in capsys.readouterr().out


def test_tail_kind_filter(tmp_path, capsys):
    log = _write_log(tmp_path / "a.jsonl", _run_records([0.1, 0.2, 0.3]))
    assert cli_main(["tail", log, "-n", "2", "--kind", "epoch"]) == 0
    lines = [
        json.loads(line)
        for line in capsys.readouterr().out.strip().splitlines()
    ]
    assert [r["epoch"] for r in lines] == [1, 2]


def test_reader_tolerates_epoch_record_missing_epoch_field(tmp_path, capsys):
    """Forward-compat contract: a future-schema epoch record that dropped
    the 'epoch' field passes validate, so summary/epochs/diff must skip
    it, not crash with a KeyError."""
    recs = _run_records([0.5, 0.8])
    recs.insert(-1, {"schema": 99, "ts": 1.0, "kind": "epoch",
                     "scalars": {"train_loss_mean": 1.0}})
    log = _write_log(tmp_path / "a.jsonl", recs)
    assert cli_main(["validate", log]) == 0
    for sub in (["summary", log], ["epochs", log], ["diff", log, log]):
        assert cli_main(sub) == 0
        capsys.readouterr()


def test_tail_rejects_nonpositive_n(tmp_path, capsys):
    log = _write_log(tmp_path / "a.jsonl", _run_records([0.1, 0.2, 0.3]))
    assert cli_main(["tail", log, "-n", "0"]) == 2
    assert cli_main(["tail", log, "-n", "-5"]) == 2
    err = capsys.readouterr().err
    assert "must be positive" in err


def test_diff_identical_runs(tmp_path, capsys):
    recs = _run_records([0.5, 0.6], config={"seed": 0})
    log_a = _write_log(tmp_path / "a.jsonl", recs)
    log_b = _write_log(tmp_path / "b.jsonl", recs)
    assert cli_main(["diff", log_a, log_b]) == 0
    text = capsys.readouterr().out
    assert "config: identical" in text
    assert "agree within tolerance" in text


def test_diff_reports_divergence_and_config_change(tmp_path, capsys):
    log_a = _write_log(tmp_path / "a.jsonl", _run_records(
        [0.5, 0.6, 0.7], config={"seed": 0, "inner_lr": 0.1},
    ))
    # same epoch 0, diverging train loss from epoch 1 on, one config delta
    log_b = _write_log(tmp_path / "b.jsonl", _run_records(
        [0.5, 0.6, 0.7], config={"seed": 0, "inner_lr": 0.4}, loss0=4.0,
    ))
    assert cli_main(["diff", log_a, log_b, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["config_changes"] == {
        "inner_lr": {"a": 0.1, "b": 0.4},
    }
    div = payload["divergence"]
    assert div["metric"] == "train_loss_mean" and div["epoch"] == 0
    assert payload["scalar_deltas"]["train_loss_mean"]["max_abs_delta"] == 2.0
    # exit code 1 only on request
    assert cli_main(["diff", log_a, log_b, "--fail-on-divergence"]) == 1


def test_validate_exit_codes(tmp_path, capsys):
    good = _write_log(tmp_path / "good.jsonl", _run_records([0.5]))
    assert cli_main(["validate", good]) == 0
    capsys.readouterr()
    bad = _write_log(
        tmp_path / "bad.jsonl",
        [{"schema": 2, "ts": 1.0, "kind": "epoch"}],  # missing fields
    )
    assert cli_main(["validate", bad]) == 1


def test_missing_file_is_exit_2(tmp_path, capsys):
    assert cli_main(["summary", str(tmp_path / "nope.jsonl")]) == 2


@pytest.mark.parametrize("sub", [["summary"], ["anomalies"], ["validate"]])
def test_cli_inspect_dispatch_is_jax_free(tmp_path, sub):
    """``python -m ...cli inspect`` must answer without importing jax —
    the postmortem path for a laptop with a scp'd log and no accelerator
    stack."""
    log = _write_log(tmp_path / "a.jsonl", _run_records(
        [0.5], anomalies=[(1, "loss_spike")],
    ))
    code = (
        "import sys\n"
        "from howtotrainyourmamlpytorch_tpu.cli import main\n"
        "try:\n"
        f"    main(['inspect', {sub[0]!r}, {log!r}])\n"
        "except SystemExit as e:\n"
        "    assert (e.code or 0) == 0, e.code\n"
        "assert 'jax' not in sys.modules, 'inspect pulled in jax'\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr[-2000:]


def test_summary_and_anomalies_surface_retraces(tmp_path, capsys):
    """Schema v4: `summary` counts retrace records (and prints the
    analysis line), `anomalies` renders a retrace row with site and
    signature — the inspect CLI stays jax-free."""
    records = _run_records([0.5])
    records.insert(-1, make_record(
        "retrace", iter=42, site="train_step[so=1]",
        signature="ab12cd34ef560078", n_signatures=2,
    ))
    log = _write_log(tmp_path / "t.jsonl", records)
    assert cli_main(["summary", log, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["retraces"] == 1
    assert cli_main(["summary", log]) == 0
    assert "1 mid-run retrace(s)" in capsys.readouterr().out
    assert cli_main(["anomalies", log]) == 0
    out = capsys.readouterr().out
    assert "retrace" in out
    assert "train_step[so=1]" in out
    assert "ab12cd34ef560078" in out


def test_summary_surfaces_audit_and_roofline_line(tmp_path, capsys):
    """Schema v5: `summary` surfaces the build-time audit record — the
    program/violation counts, the SPMD audit mesh and the flagship
    roofline prediction — as the audit line (still jax-free)."""
    records = _run_records([0.5])
    records.insert(-1, make_record(
        "analysis", programs=12, violations=0, mesh="1x8",
        roofline={
            "program": "train_step[so=1]", "bound": "memory",
            "predicted_hfu": 0.24, "predicted_mfu": 0.031,
            "flops_per_task": 2.7e6,
        },
    ))
    log = _write_log(tmp_path / "t.jsonl", records)
    assert cli_main(["summary", log, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["audit"]["programs"] == 12
    assert payload["audit"]["mesh"] == "1x8"
    assert payload["audit"]["roofline"]["bound"] == "memory"
    assert cli_main(["summary", log]) == 0
    out = capsys.readouterr().out
    assert "audit: 12 program(s), 0 violation(s) on mesh 1x8" in out
    assert "roofline[train_step[so=1]]: memory-bound" in out
    assert "predicted mfu 0.031" in out


def test_summary_without_audit_record_omits_audit_line(tmp_path, capsys):
    records = _run_records([0.5])
    log = _write_log(tmp_path / "t.jsonl", records)
    assert cli_main(["summary", log, "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["audit"] is None
    assert cli_main(["summary", log]) == 0
    assert "audit:" not in capsys.readouterr().out


def test_summary_surfaces_overlap_line(tmp_path, capsys):
    """Schema v7: `summary` condenses the dispatch records' epoch-boundary
    overlap fields into the overlap line — mean/total hidden milliseconds,
    skipped phase-transition blocks, and the accumulation setting."""
    records = _run_records([0.5])
    records.insert(-1, make_record(
        "dispatch", epoch=0, train_step_time_ms=10.0,
        overlap_ms=12.5, boundary_overlaps=2, accum_steps=4,
    ))
    records.insert(-1, make_record(
        "dispatch", epoch=1, train_step_time_ms=10.0,
        overlap_ms=7.5, boundary_overlaps=2, accum_steps=4,
    ))
    log = _write_log(tmp_path / "t.jsonl", records)
    assert cli_main(["summary", log, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["overlap"]["overlap_ms_mean"] == 10.0
    assert payload["overlap"]["overlap_ms_total"] == 20.0
    assert payload["overlap"]["boundary_overlaps_total"] == 4
    assert payload["overlap"]["accum_steps"] == 4
    assert cli_main(["summary", log]) == 0
    out = capsys.readouterr().out
    assert "overlap: boundary overlap 10.0ms/epoch" in out
    assert "(20.0ms total hidden)" in out
    assert "4 phase-transition block(s) skipped" in out
    assert "accum_steps=4" in out


def test_summary_pre_v7_log_omits_overlap_line(tmp_path, capsys):
    """A log whose dispatch records predate the v7 fields gets no overlap
    line (and a null payload entry) — never a crash."""
    log = _write_log(tmp_path / "old.jsonl", _run_records([0.5]))
    assert cli_main(["summary", log, "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["overlap"] is None
    assert cli_main(["summary", log]) == 0
    assert "overlap:" not in capsys.readouterr().out


def test_summary_without_retraces_prints_no_analysis_line(tmp_path, capsys):
    log = _write_log(tmp_path / "t.jsonl", _run_records([0.5]))
    assert cli_main(["summary", log]) == 0
    assert "mid-run retrace" not in capsys.readouterr().out


def test_summary_surfaces_elastic_drain_and_resume_line(tmp_path, capsys):
    """Schema v6: `summary` condenses the `elastic` records — drain
    protocol progress and the last topology-change resume (old -> new
    process count + episode cursor) — into the elastic line (jax-free)."""
    records = _run_records([0.5])
    for rec in (
        make_record("elastic", event="drain_request", iter=5, signal=15),
        make_record("elastic", event="drain_commit", iter=6, drain_iter=8,
                    signal=15, requested_by=1),
        make_record("elastic", event="drain_ack", iter=8, drain_iter=8),
        make_record("elastic", event="resume", old_process_count=2,
                    new_process_count=3, iter=8, episode_cursor=48),
    ):
        records.insert(-1, rec)
    log = _write_log(tmp_path / "t.jsonl", records)
    assert cli_main(["summary", log, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["elastic"]["drain_requests"] == 1
    assert payload["elastic"]["drain_commits"] == 1
    assert payload["elastic"]["drain_acks"] == 1
    assert payload["elastic"]["resumes"] == 1
    assert payload["elastic"]["last_resume"] == {
        "old_process_count": 2, "new_process_count": 3, "iter": 8,
        "episode_cursor": 48,
    }
    assert cli_main(["summary", log]) == 0
    out = capsys.readouterr().out
    assert (
        "elastic: 1 drain request(s), 1 commit(s), 1 ack(s), "
        "1 elastic resume(s)" in out
    )
    assert "last resume 2 -> 3 process(es) @ iter 8 (episode cursor 48)" in out


def test_summary_without_elastic_records_omits_elastic_line(tmp_path, capsys):
    log = _write_log(tmp_path / "t.jsonl", _run_records([0.5]))
    assert cli_main(["summary", log, "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["elastic"] is None
    assert cli_main(["summary", log]) == 0
    assert "elastic:" not in capsys.readouterr().out


def test_summary_per_bucket_serving_breakdown(tmp_path, capsys):
    """Schema v10: the serving line grows a per-(program, bucket, shots)
    breakdown — p50/p95 and cache-hit rate per compiled dispatch
    signature — and stays crash-free on records missing the newer
    fields (a v8-era log groups under program 'adapt')."""
    records = _run_records([0.5])
    records.insert(-1, make_record(
        "serving", event="dispatch", tenants=2, bucket=2, shots=1,
        queue_ms=0.5, adapt_ms=4.0, program="adapt", ingest="f32",
        ingest_bytes=1024, cache_hits=0,
    ))
    records.insert(-1, make_record(
        "serving", event="dispatch", tenants=4, bucket=4, shots=1,
        queue_ms=0.5, adapt_ms=8.0, program="adapt", ingest="f32",
        ingest_bytes=2048, cache_hits=0,
    ))
    records.insert(-1, make_record(
        "serving", event="dispatch", tenants=2, bucket=2, shots=1,
        queue_ms=0.1, adapt_ms=1.0, program="predict", ingest="f32",
        ingest_bytes=512, cache_hits=2,
    ))
    # a v8-era dispatch record: no program/cache fields at all
    records.insert(-1, make_record(
        "serving", event="dispatch", tenants=1, bucket=1, shots=2,
        queue_ms=0.2, adapt_ms=3.0,
    ))
    log = _write_log(tmp_path / "sv.jsonl", records)
    assert cli_main(["summary", log, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    per_bucket = payload["serving"]["per_bucket"]
    assert set(per_bucket) == {
        "adapt/b2/s1", "adapt/b4/s1", "predict/b2/s1", "adapt/b1/s2",
    }
    assert per_bucket["adapt/b2/s1"]["adapt_ms_p50"] == 4.0
    assert per_bucket["predict/b2/s1"]["cache_hit_rate"] == 1.0
    assert per_bucket["adapt/b1/s2"]["dispatches"] == 1
    assert per_bucket["adapt/b1/s2"]["cache_hit_rate"] is None
    assert cli_main(["summary", log]) == 0
    out = capsys.readouterr().out
    assert "serving[adapt/b2/s1]:" in out
    assert "serving[predict/b2/s1]:" in out
    assert "cache hit 100%" in out


def test_summary_pre_v10_serving_log_never_crashes(tmp_path, capsys):
    """A v8-era serving log (no decomposition, no program field) renders
    the aggregate line and a degraded per-bucket breakdown — exit 0."""
    records = _run_records([0.5])
    records.insert(-1, {
        "schema": 8, "ts": 1.0, "kind": "serving", "event": "dispatch",
        "tenants": 3, "bucket": 4, "shots": 1, "queue_ms": 0.9,
        "adapt_ms": 4.4,
    })
    log = _write_log(tmp_path / "v8.jsonl", records)
    assert cli_main(["summary", log]) == 0
    assert "serving[adapt/b4/s1]:" in capsys.readouterr().out


# -- cli trace ---------------------------------------------------------------


def _span(name, cat, trace_id, span_id, start_ms, dur_ms, parent=None,
          **attrs):
    fields = dict(
        name=name, cat=cat, trace_id=trace_id, span_id=span_id,
        start_ms=start_ms, dur_ms=dur_ms, tid="main",
    )
    if parent:
        fields["parent_id"] = parent
    if attrs:
        fields["attrs"] = attrs
    return make_record("span", **fields)


def _span_log_records():
    tid = "ab12cd34ef567890"
    return _run_records([0.5])[:-1] + [
        _span("request", "serving", tid, "s1", 100.0, 10.0,
              request_id=f"{tid}-r1", shots=1),
        _span("queue", "serving", tid, "s2", 100.0, 2.0, parent="s1",
              shots=1),
        _span("assemble", "serving", tid, "s3", 102.0, 1.0, parent="s1",
              program="adapt", bucket=2, shots=1),
        _span("dispatch", "serving", tid, "s4", 103.0, 5.0, parent="s1",
              program="adapt", bucket=2, shots=1),
        _span("sync", "serving", tid, "s5", 108.0, 2.0, parent="s1",
              program="adapt", bucket=2, shots=1),
        _span("train_dispatch", "train", tid, "s6", 120.0, 30.0, iter=0),
        make_record("trace", action="start", trace_dir="/tmp/prof0",
                    steps=4, trace_id=tid, on_demand=True),
        make_record("trace", action="stop", trace_dir="/tmp/prof0",
                    trace_id=tid, on_demand=True),
        make_record("run_end"),
    ]


def test_trace_cli_writes_chrome_trace_and_summary(tmp_path, capsys):
    from howtotrainyourmamlpytorch_tpu.tools.trace_cli import main as trace_main

    log = _write_log(tmp_path / "run.jsonl", _span_log_records())
    assert trace_main([log]) == 0
    out = capsys.readouterr().out
    assert "6 span(s)" in out
    assert "adapt/b2/s1" in out
    assert "train_dispatch" in out
    assert "device-profile windows" in out
    artifact = tmp_path / "run.trace.json"
    trace = json.loads(artifact.read_text())
    xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert len(xs) == 6
    ts = [e["ts"] for e in xs]
    assert ts == sorted(ts)
    # the request root's children cover queue -> dispatch -> sync
    kids = {e["name"] for e in xs if e["args"].get("parent_id") == "s1"}
    assert {"queue", "assemble", "dispatch", "sync"} <= kids
    # the decomposition identity: stage means sum to the request e2e
    # (2 + 1 + 5 + 2 == 10) within the exporter's rounding
    assert trace_main([log, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    row = payload["serving"]["adapt/b2/s1"]
    stage_sum = sum(
        row[f"{s}_ms_mean"] or 0.0
        for s in ("assemble", "dispatch", "sync")
    ) + payload["serving"]["*/b*/s1"]["queue_ms_mean"]
    e2e = payload["serving"]["*/b*/s1"]["request_ms_mean"]
    assert stage_sum == pytest.approx(e2e, rel=0.05)


def test_trace_cli_span_free_log_exits_zero(tmp_path, capsys):
    from howtotrainyourmamlpytorch_tpu.tools.trace_cli import main as trace_main

    log = _write_log(tmp_path / "bare.jsonl", _run_records([0.4]))
    out_path = tmp_path / "bare.trace.json"
    assert trace_main([log, "--out", str(out_path)]) == 0
    assert "no span records" in capsys.readouterr().out
    trace = json.loads(out_path.read_text())
    assert trace["traceEvents"] == []


def test_trace_cli_missing_log_exits_2(tmp_path, capsys):
    from howtotrainyourmamlpytorch_tpu.tools.trace_cli import main as trace_main

    assert trace_main([str(tmp_path / "nope.jsonl")]) == 2


def test_cli_trace_dispatch_is_jax_free(tmp_path):
    """`python -m ...cli trace` must answer without importing jax — the
    same laptop-postmortem contract as inspect."""
    log = _write_log(tmp_path / "t.jsonl", _span_log_records())
    code = (
        "import sys\n"
        "from howtotrainyourmamlpytorch_tpu.cli import main\n"
        "try:\n"
        f"    main(['trace', {log!r}, '--out', '-'])\n"
        "except SystemExit as e:\n"
        "    assert (e.code or 0) == 0, e.code\n"
        "assert 'jax' not in sys.modules, 'trace pulled in jax'\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr[-2000:]


def test_summary_per_replica_serving_breakdown(tmp_path, capsys):
    """Schema v11: dispatch records tagged with replica_id grow a
    per-replica summary line (traffic spread + per-replica cache
    locality) and rollover events are counted; pre-v11 records without
    the field produce no replica rows and never crash."""
    records = _run_records([0.5])
    records.insert(-1, make_record(
        "serving", event="dispatch", tenants=2, bucket=2, shots=1,
        queue_ms=0.5, adapt_ms=4.0, program="adapt", ingest="f32",
        ingest_bytes=1024, cache_hits=0, replica_id=0,
    ))
    records.insert(-1, make_record(
        "serving", event="dispatch", tenants=4, bucket=4, shots=1,
        queue_ms=0.5, adapt_ms=8.0, program="adapt", ingest="f32",
        ingest_bytes=2048, cache_hits=2, replica_id=1,
    ))
    records.insert(-1, make_record(
        "serving", event="dispatch", tenants=2, bucket=2, shots=1,
        queue_ms=0.1, adapt_ms=2.0, program="predict", ingest="f32",
        ingest_bytes=512, cache_hits=2, replica_id=1,
    ))
    records.insert(-1, make_record(
        "serving", event="rollover", replica_id=0, old_iter=0,
        new_iter=9, swap_ms=0.05, xla_compiles_at_swap=0,
    ))
    # a malformed replica_id must be skipped, never crash the summary
    records.insert(-1, make_record(
        "serving", event="dispatch", tenants=1, bucket=1, shots=1,
        queue_ms=0.1, adapt_ms=1.0, program="adapt", ingest="f32",
        replica_id="not-an-int",
    ))
    log = _write_log(tmp_path / "pool.jsonl", records)
    assert cli_main(["summary", log, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    sv = payload["serving"]
    assert sv["rollovers"] == 1
    assert set(sv["per_replica"]) == {"0", "1"}
    assert sv["per_replica"]["0"]["tenants"] == 2
    assert sv["per_replica"]["1"]["tenants"] == 6
    assert sv["per_replica"]["1"]["cache_hit_rate"] == round(4 / 6, 4)
    assert cli_main(["summary", log]) == 0
    out = capsys.readouterr().out
    assert "serving[replica 0]:" in out
    assert "serving[replica 1]:" in out
    assert "2 replica(s)" in out
    assert "1 rollover(s)" in out


def test_summary_pre_v11_serving_log_has_no_replica_rows(tmp_path, capsys):
    """A pre-v11 log (serving records without replica_id) keeps the
    exact pre-pool summary shape: no per-replica lines, no rollovers,
    exit 0."""
    records = _run_records([0.5])
    records.insert(-1, {
        "schema": 10, "ts": 1.0, "kind": "serving", "event": "dispatch",
        "tenants": 3, "bucket": 4, "shots": 1, "queue_ms": 0.9,
        "adapt_ms": 4.4, "program": "adapt", "ingest": "f32",
    })
    log = _write_log(tmp_path / "v10.jsonl", records)
    assert cli_main(["summary", log, "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["serving"]["per_replica"] == {}
    assert payload["serving"]["rollovers"] == 0
    assert cli_main(["summary", log]) == 0
    out = capsys.readouterr().out
    assert "serving[replica" not in out
    assert "rollover" not in out
