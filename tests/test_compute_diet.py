"""The PR-16 inner-loop compute diet: three independently toggleable levers.

* ``im2col_hoist`` — layer 1's patch extraction computed once per task
  outside the inner ``lax.scan`` (``models.vgg.layer1_patches``) and
  threaded as a scan invariant. Bit-exact by construction (the hoisted
  tensor IS what the inline extraction would produce), pinned here with
  ``assert_array_equal`` at both the forward and the meta-gradient level.
* ``bn_stats_impl='fused'`` — one pass over the activations computing
  sum + sum-of-squares in f32 instead of mean-then-var. Tolerance-
  bounded, NOT bit-exact; the bounds pinned here (f32 and bf16, first
  and second order) are ~5x above the measured deviation.
* ``pool_impl='reshape'`` — already bit-exact at the op level
  (test_conv_impl pins it); here the train-step-level equivalence.

Plus the lever-off/on HLO census assertions (the fused stats must SHRINK
the reduce census; the hoist must shrink the rolled scan's im2col ops),
the config-time validation / 'auto' resolution rules, the tuning-table
consult, the bench comparability invariant (diet knobs must not move
``xla_flops_per_task`` — they cut time, not work), and the serving-export
staleness key (a tuning-table flip of a resolved knob must invalidate
AOT artifacts whose config fingerprint is unchanged).
"""

from __future__ import annotations

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from conftest import make_micro_cfg, make_synthetic_batch
from numpy.testing import assert_array_equal

from howtotrainyourmamlpytorch_tpu.analysis import autotune
from howtotrainyourmamlpytorch_tpu.analysis.contracts import hlo_op_census
from howtotrainyourmamlpytorch_tpu.core import maml, msl
from howtotrainyourmamlpytorch_tpu.models import vgg
from howtotrainyourmamlpytorch_tpu.ops import functional as F
from howtotrainyourmamlpytorch_tpu.serving import export


def _rand(shape, seed=0, dtype=jnp.float32):
    return jnp.asarray(
        np.random.RandomState(seed).randn(*shape).astype(np.float32)
    ).astype(dtype)


def _f32(x):
    return np.asarray(x, dtype=np.float32)


@pytest.fixture(autouse=True)
def _fresh_tuning_cache():
    """Every test here sees (and leaves behind) a clean tuning-table
    cache — several tests point MAML_TUNING_TABLE at temp files."""
    autotune.clear_cache()
    yield
    autotune.clear_cache()


# -- fused BN statistics: op level --------------------------------------------

# Pinned deviation bounds for 'fused' vs 'twopass' — ~5x above measured
# (f32 forward max |diff| ~2e-6 on these shapes; bf16 pays its 2^-8 eps
# through the twopass arm's low-precision accumulation, the fused arm
# accumulates in f32 either way).
_BN_TOL = {
    "float32": {"fwd": 1e-5, "grad": 1e-4, "grad2": 1e-3},
    "bfloat16": {"fwd": 5e-2, "grad": 5e-2, "grad2": 1e-1},
}


def _bn_args(dtype):
    x = _rand((8, 7, 9, 5), 0, dtype)
    gamma = (_rand((5,), 1) * 0.1 + 1.0).astype(dtype)
    beta = (_rand((5,), 2) * 0.1).astype(dtype)
    rm = jnp.zeros((5,), dtype)
    rv = jnp.ones((5,), dtype)
    return x, gamma, beta, rm, rv


@pytest.mark.parametrize("dtype_name", ["float32", "bfloat16"])
def test_fused_bn_forward_and_running_stats_within_bound(dtype_name):
    dtype = jnp.dtype(dtype_name)
    x, gamma, beta, rm, rv = _bn_args(dtype)
    out_t, nm_t, nv_t = F.batch_norm(x, gamma, beta, rm, rv,
                                     stats_impl="twopass")
    out_f, nm_f, nv_f = F.batch_norm(x, gamma, beta, rm, rv,
                                     stats_impl="fused")
    assert out_f.dtype == out_t.dtype == dtype
    tol = _BN_TOL[dtype_name]["fwd"]
    np.testing.assert_allclose(_f32(out_f), _f32(out_t), atol=tol, rtol=tol)
    np.testing.assert_allclose(_f32(nm_f), _f32(nm_t), atol=tol, rtol=tol)
    np.testing.assert_allclose(_f32(nv_f), _f32(nv_t), atol=tol, rtol=tol)


@pytest.mark.parametrize("dtype_name", ["float32", "bfloat16"])
def test_fused_bn_first_and_second_order_grads_within_bound(dtype_name):
    dtype = jnp.dtype(dtype_name)
    x, gamma, beta, _, _ = _bn_args(dtype)

    def loss(impl):
        def f(x, gamma, beta):
            out, _, _ = F.batch_norm(x, gamma, beta, None, None,
                                     stats_impl=impl)
            return jnp.mean(jnp.tanh(out).astype(jnp.float32) ** 2)

        return f

    tol = _BN_TOL[dtype_name]
    g_t = jax.grad(loss("twopass"), argnums=(0, 1, 2))(x, gamma, beta)
    g_f = jax.grad(loss("fused"), argnums=(0, 1, 2))(x, gamma, beta)
    for a, b in zip(g_f, g_t):
        np.testing.assert_allclose(_f32(a), _f32(b),
                                   atol=tol["grad"], rtol=tol["grad"])

    def meta(impl):
        def m(x, gamma, beta):
            g = jax.grad(loss(impl))(x, gamma, beta)
            return jnp.sum(jnp.tanh(g.astype(jnp.float32)))

        return m

    gg_t = jax.grad(meta("twopass"))(x, gamma, beta)
    gg_f = jax.grad(meta("fused"))(x, gamma, beta)
    np.testing.assert_allclose(_f32(gg_f), _f32(gg_t),
                               atol=tol["grad2"], rtol=tol["grad2"])


def test_batch_norm_rejects_unknown_stats_impl():
    x, gamma, beta, rm, rv = _bn_args(jnp.float32)
    with pytest.raises(ValueError, match="stats_impl"):
        F.batch_norm(x, gamma, beta, rm, rv, stats_impl="onepass")


# -- hoisted layer-1 patches: forward level -----------------------------------


def _apply_cfg(**overrides):
    base = dict(conv_impl="im2col", max_pooling=True)
    base.update(overrides)
    return make_micro_cfg(**base)


@pytest.mark.parametrize("dtype_name", ["float32", "bfloat16"])
@pytest.mark.parametrize("pad", ["off", "tile"])
def test_hoisted_patches_forward_bit_exact(dtype_name, pad):
    """``apply(..., x_patches=layer1_patches(...))`` must be bitwise the
    self-contained forward — logits AND updated BN state."""
    cfg = _apply_cfg(compute_dtype=dtype_name, pad_channels=pad,
                     im2col_hoist="on")
    params, bn = vgg.init(cfg, jax.random.PRNGKey(0))
    x = _rand((8,) + cfg.im_shape, 3)
    patches = vgg.layer1_patches(cfg, x)
    assert patches is not None
    out0, bn0 = vgg.apply(cfg, params, bn, x, 0, training=True)
    out1, bn1 = vgg.apply(cfg, params, bn, x, 0, training=True,
                          x_patches=patches)
    assert_array_equal(np.asarray(out0), np.asarray(out1))
    assert sorted(bn0) == sorted(bn1)
    for k in bn0:
        assert_array_equal(np.asarray(bn0[k]), np.asarray(bn1[k]))


def test_layer1_patches_none_when_inapplicable():
    """The hoist only exists for patch-consuming conv lowerings under the
    conv-first block; everywhere else the helper says so with None."""
    x = _rand((4, 8, 8, 1), 0)
    assert vgg.layer1_patches(_apply_cfg(conv_impl="lax"), x) is None
    assert vgg.layer1_patches(
        _apply_cfg(block_order="norm_conv_relu"), x
    ) is None
    assert vgg.layer1_patches(_apply_cfg(im2col_hoist="off"), x) is None
    assert vgg.layer1_patches(_apply_cfg(im2col_hoist="on"), x) is not None


def test_conv_patches_matches_inline_extraction():
    """conv2d(patches=conv_patches(x, ...)) == conv2d(x) bitwise, padded
    and unpadded channels."""
    x = _rand((3, 9, 9, 5), 0)
    w = _rand((3, 3, 5, 7), 1)
    b = _rand((7,), 2)
    for pad_ch in ("off", "tile"):
        for impl in ("im2col", "gemm"):
            inline = F.conv2d(x, w, b, 2, 1, impl=impl, pad_channels=pad_ch)
            patches = F.conv_patches(x, 3, 3, 2, 1, pad_channels=pad_ch)
            hoisted = F.conv2d(x, w, b, 2, 1, impl=impl,
                               pad_channels=pad_ch, patches=patches)
            assert_array_equal(np.asarray(inline), np.asarray(hoisted))


# -- train-step equivalence matrix (per lever) --------------------------------


def _weights(cfg):
    return msl.loss_weights_for(
        cfg.number_of_training_steps_per_iter,
        cfg.use_multi_step_loss_optimization, True, 0,
        cfg.multi_step_loss_num_epochs,
    )


def _grads(cfg, second_order):
    state = maml.init_state(cfg, seed=0)
    x_s, y_s, x_t, y_t = make_synthetic_batch(cfg, seed=1)
    fn = jax.jit(maml.make_grads_fn(cfg, second_order))
    loss, grads = fn(state, x_s, y_s, x_t, y_t, _weights(cfg))
    return np.asarray(loss), jax.tree_util.tree_map(np.asarray, grads)


def _assert_grads_close(ga, gb, atol, rtol):
    la, ta = jax.tree_util.tree_flatten(ga)
    lb, tb = jax.tree_util.tree_flatten(gb)
    assert ta == tb
    for a, b in zip(la, lb):
        np.testing.assert_allclose(_f32(a), _f32(b), atol=atol, rtol=rtol)


def _assert_grads_equal(ga, gb):
    la, ta = jax.tree_util.tree_flatten(ga)
    lb, tb = jax.tree_util.tree_flatten(gb)
    assert ta == tb
    for a, b in zip(la, lb):
        assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize(
    "second_order",
    [False, pytest.param(True, marks=pytest.mark.slow)],
)
def test_hoist_meta_grads_bit_exact(second_order):
    """The fast-lane hoist pin: meta-gradients with the scan-invariant
    patch tensor threaded are BITWISE those of the inline extraction."""
    off = make_micro_cfg(conv_impl="im2col", im2col_hoist="off",
                         second_order=second_order)
    on = off.replace(im2col_hoist="on")
    loss_off, g_off = _grads(off, second_order)
    loss_on, g_on = _grads(on, second_order)
    assert_array_equal(loss_off, loss_on)
    _assert_grads_equal(g_off, g_on)


@pytest.mark.parametrize(
    "second_order",
    [False, pytest.param(True, marks=pytest.mark.slow)],
)
def test_fused_bn_meta_grads_within_bound(second_order):
    tp = make_micro_cfg(bn_stats_impl="twopass", im2col_hoist="off")
    fu = tp.replace(bn_stats_impl="fused")
    loss_t, g_t = _grads(tp, second_order)
    loss_f, g_f = _grads(fu, second_order)
    np.testing.assert_allclose(loss_f, loss_t, atol=1e-5, rtol=1e-5)
    _assert_grads_close(g_f, g_t, atol=1e-4, rtol=1e-3)


@pytest.mark.parametrize(
    "second_order",
    [False, pytest.param(True, marks=pytest.mark.slow)],
)
def test_reshape_pool_meta_grads_match_reduce_window(second_order):
    rw = make_micro_cfg(max_pooling=True, pool_impl="reduce_window",
                        im2col_hoist="off")
    rs = rw.replace(pool_impl="reshape")
    loss_a, g_a = _grads(rw, second_order)
    loss_b, g_b = _grads(rs, second_order)
    np.testing.assert_allclose(loss_b, loss_a, atol=1e-6, rtol=1e-6)
    _assert_grads_close(g_b, g_a, atol=1e-6, rtol=1e-6)


@pytest.mark.slow
@pytest.mark.parametrize("dtype_name", ["float32", "bfloat16"])
@pytest.mark.parametrize("second_order", [False, True])
@pytest.mark.parametrize("pad", ["off", "tile"])
@pytest.mark.parametrize("axis_mode", ["vmap", "map"])
def test_diet_equivalence_matrix(dtype_name, second_order, pad, axis_mode):
    """The full lever matrix: {f32,bf16} x {first,second order} x
    {pad_channels off/tile} x {vmap,map}. All three levers flipped at
    once against the all-off program — hoist and pool are bit-exact, so
    the composite bound is the fused-BN bound alone."""
    off = make_micro_cfg(
        compute_dtype=dtype_name, pad_channels=pad,
        task_axis_mode=axis_mode, conv_impl="im2col", max_pooling=True,
        second_order=second_order,
        bn_stats_impl="twopass", im2col_hoist="off",
        pool_impl="reduce_window",
    )
    on = off.replace(bn_stats_impl="fused", im2col_hoist="on",
                     pool_impl="reshape")
    loss_off, g_off = _grads(off, second_order)
    loss_on, g_on = _grads(on, second_order)
    # micro-config meta-gradients are O(1); the absolute bound carries
    tol = 1e-3 if dtype_name == "float32" else 1e-1
    np.testing.assert_allclose(loss_on, loss_off, atol=tol, rtol=tol)
    _assert_grads_close(g_on, g_off, atol=tol, rtol=tol)


# -- HLO census: the diet must shrink the program -----------------------------


def _census(cfg, second_order=True):
    state = maml.init_state(cfg, seed=0)
    x_s, y_s, x_t, y_t = make_synthetic_batch(cfg, seed=0)
    fn = jax.jit(maml.make_grads_fn(cfg, second_order))
    txt = fn.lower(state, x_s, y_s, x_t, y_t,
                   _weights(cfg)).compile().as_text()
    return hlo_op_census(txt)


@pytest.mark.slow
def test_fused_bn_shrinks_reduce_census():
    """The CI census-shrink gate's in-suite twin: the one-pass statistics
    must lower to strictly fewer reduce ops on the second-order program."""
    tp = make_micro_cfg(bn_stats_impl="twopass", im2col_hoist="off")
    c_tp = _census(tp)
    c_fu = _census(tp.replace(bn_stats_impl="fused"))
    assert c_fu.get("reduce", 0) < c_tp.get("reduce", 0), (
        f"fused={c_fu.get('reduce')} twopass={c_tp.get('reduce')}"
    )


@pytest.mark.slow
def test_hoist_shrinks_rolled_remat_census():
    """Where the hoist materially changes the program: a ROLLED inner
    scan (num_steps > 8) under remat. On short unrolled scans XLA's CSE
    already dedups the step-invariant extraction (the hoist is a no-op
    by census there — still bit-exact), but remat re-extracts inside
    every loop-body backward region; hoisting must strip those: strictly
    fewer slice AND concatenate ops in the compiled train step."""
    off = make_micro_cfg(conv_impl="im2col", im2col_hoist="off",
                         number_of_training_steps_per_iter=10,
                         use_remat=True)
    on = off.replace(im2col_hoist="on")

    def census_step(cfg):
        state = maml.init_state(cfg, seed=0)
        x_s, y_s, x_t, y_t = make_synthetic_batch(cfg, seed=0)
        fn = jax.jit(maml.make_train_step(cfg, second_order=True),
                     donate_argnums=(0,))
        txt = fn.lower(state, x_s, y_s, x_t, y_t, _weights(cfg),
                       jnp.float32(1e-3)).compile().as_text()
        return hlo_op_census(txt)

    c_off, c_on = census_step(off), census_step(on)
    assert c_on.get("slice", 0) < c_off.get("slice", 0), (
        f"hoisted slice={c_on.get('slice')} inline={c_off.get('slice')}"
    )
    assert c_on.get("concatenate", 0) < c_off.get("concatenate", 0)


def _compiled_text(cfg, second_order=True):
    state = maml.init_state(cfg, seed=0)
    x_s, y_s, x_t, y_t = make_synthetic_batch(cfg, seed=0)
    fn = jax.jit(maml.make_grads_fn(cfg, second_order))
    return fn.lower(state, x_s, y_s, x_t, y_t,
                    _weights(cfg)).compile().as_text()


@pytest.mark.slow
def test_reshape_pool_removes_reduce_window_census():
    """The pool lever's census claim: 'reshape' lowers max-pooling with
    zero pool-origin reduce-window ops.  The count does not drop to an
    absolute zero on CPU because XLA lowers the MSL per-step scatter to
    reduce-window too — those are pool-independent, so the honest
    assertions are (a) strict shrink and (b) every residual
    reduce-window in the reshape program traces to a scatter."""
    rw = make_micro_cfg(max_pooling=True, pool_impl="reduce_window",
                        im2col_hoist="off")
    t_rw = _compiled_text(rw)
    t_rs = _compiled_text(rw.replace(pool_impl="reshape"))
    c_rw, c_rs = hlo_op_census(t_rw), hlo_op_census(t_rs)
    assert c_rw.get("reduce-window", 0) > c_rs.get("reduce-window", 0), (
        f"reduce_window={c_rw.get('reduce-window')} "
        f"reshape={c_rs.get('reduce-window')}"
    )
    def pool_windows(t):
        # pool-origin ops reduce a spatial 2x2 window; the scatter-lowered
        # residuals reduce class-axis windows (e.g. size=1x32x2)
        return [l for l in t.splitlines()
                if "reduce-window(" in l and "x2x2x" in l]

    assert pool_windows(t_rw), "reduce_window arm lost its pool ops?"
    assert not pool_windows(t_rs), (
        f"pool-origin reduce-window survived:\n{pool_windows(t_rs)}"
    )


@pytest.mark.slow
def test_diet_knobs_preserve_xla_flops():
    """The bench comparability invariant: the levers cut TIME, not WORK —
    XLA's own flop count for the compiled step must agree within 5%
    across the diet matrix (the bench.py cross-baseline assertion's
    in-suite twin)."""
    # a GEMM-dominated geometry, like every real workload this invariant
    # guards (on reduction-dominated toy shapes the removed BN/pool
    # bookkeeping is itself a visible flop fraction)
    off = make_micro_cfg(conv_impl="im2col", max_pooling=True,
                         image_height=16, image_width=16,
                         cnn_num_filters=8, num_stages=2,
                         bn_stats_impl="twopass", im2col_hoist="off",
                         pool_impl="reduce_window")
    on = off.replace(bn_stats_impl="fused", im2col_hoist="on",
                     pool_impl="reshape")

    def flops(cfg):
        state = maml.init_state(cfg, seed=0)
        x_s, y_s, x_t, y_t = make_synthetic_batch(cfg, seed=0)
        fn = jax.jit(maml.make_grads_fn(cfg, True))
        cost = fn.lower(state, x_s, y_s, x_t, y_t,
                        _weights(cfg)).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return float(cost.get("flops", 0.0))

    f_off, f_on = flops(off), flops(on)
    assert f_off > 0 and f_on > 0
    assert abs(f_on / f_off - 1.0) < 0.05, (f_off, f_on)


# -- config validation + 'auto' resolution ------------------------------------


def test_config_rejects_invalid_diet_knob_values():
    with pytest.raises(ValueError, match="bn_stats_impl"):
        make_micro_cfg(bn_stats_impl="onepass")
    with pytest.raises(ValueError, match="im2col_hoist"):
        make_micro_cfg(im2col_hoist="yes")
    with pytest.raises(ValueError, match="pool_impl"):
        make_micro_cfg(pool_impl="stride")


def test_config_rejects_contradictory_hoist_combos():
    """'on' is a promise the lowering consumes patches; combinations
    where it cannot are config-build errors, not silent no-ops."""
    with pytest.raises(ValueError, match="im2col_hoist"):
        make_micro_cfg(im2col_hoist="on", conv_impl="lax")
    with pytest.raises(ValueError, match="im2col_hoist"):
        make_micro_cfg(im2col_hoist="on", block_order="norm_conv_relu")
    # 'auto' with the same combos is fine: it resolves to off
    assert make_micro_cfg(conv_impl="lax").resolved_im2col_hoist is False
    assert make_micro_cfg(
        block_order="norm_conv_relu"
    ).resolved_im2col_hoist is False


def test_config_rejects_vanishing_pool_geometry():
    """max_pooling halves each stage; a geometry whose pool input drops
    below the 2x2 window is rejected at build, naming the stage."""
    with pytest.raises(ValueError, match="geometry vanishes"):
        make_micro_cfg(max_pooling=True, conv_padding=False,
                       image_height=14, image_width=14, num_stages=3)
    # one fewer stage is legal
    make_micro_cfg(max_pooling=True, conv_padding=False,
                   image_height=14, image_width=14, num_stages=2)


def test_resolved_diet_knobs_cpu_heuristics():
    cfg = make_micro_cfg()
    # explicit beats everything
    assert cfg.replace(
        bn_stats_impl="twopass"
    ).resolved_bn_stats_impl == "twopass"
    assert cfg.replace(im2col_hoist="off").resolved_im2col_hoist is False
    # CPU 'auto': fused stats, reshape pool, hoist on (im2col conv)
    assert cfg.resolved_bn_stats_impl == "fused"
    assert cfg.resolved_pool_impl == "reshape"
    assert cfg.replace(conv_impl="im2col").resolved_im2col_hoist is True


def _diet_table(tmp_path, name="diet.json", **knobs):
    kind = jax.devices()[0].device_kind
    entry = {
        "conv_impl": "im2col", "pad_channels": "off",
        "remat_policy": "full", "meta_accum_steps": 1,
        "tasks_per_sec_per_chip": 10.0,
    }
    entry.update(knobs)
    path = os.path.join(str(tmp_path), name)
    with open(path, "w") as f:
        json.dump({"version": autotune.TUNING_VERSION,
                   "entries": {autotune.table_key(kind, "float32"): entry}},
                  f)
    autotune.clear_cache()
    return path


def test_auto_diet_knobs_consult_tuning_table(tmp_path, monkeypatch):
    """A measured winner beats the heuristic: a table pinning the
    non-CPU-default values flips both sweepable knobs. The hoist is NOT
    table-consulted — it is strictly-less-work, no sweep axis."""
    path = _diet_table(tmp_path, bn_stats_impl="twopass",
                       pool_impl="reduce_window")
    monkeypatch.setenv(autotune.TUNING_TABLE_ENV, path)
    autotune.clear_cache()
    cfg = make_micro_cfg()
    assert cfg.resolved_bn_stats_impl == "twopass"
    assert cfg.resolved_pool_impl == "reduce_window"
    # explicit still beats the table
    assert cfg.replace(
        bn_stats_impl="fused"
    ).resolved_bn_stats_impl == "fused"
    assert cfg.replace(pool_impl="reshape").resolved_pool_impl == "reshape"
    # a table without the PR-16 knobs (pre-PR-16 file) keeps heuristics
    old = _diet_table(tmp_path, name="old.json")
    monkeypatch.setenv(autotune.TUNING_TABLE_ENV, old)
    autotune.clear_cache()
    cfg = make_micro_cfg()
    assert cfg.resolved_bn_stats_impl == "fused"
    assert cfg.resolved_pool_impl == "reshape"


# -- serving export: resolved knobs key the artifacts -------------------------


def test_export_manifest_records_resolved_diet_knobs():
    cfg = make_micro_cfg(max_pooling=True)
    exp = export._manifest_expectation(cfg, "f32", False, [1], [1])
    assert exp["bn_stats_impl"] == cfg.resolved_bn_stats_impl
    assert exp["im2col_hoist"] == cfg.resolved_im2col_hoist
    assert exp["pool_impl"] == cfg.resolved_pool_impl
    assert exp["conv_impl"] == cfg.resolved_conv_impl


def test_export_artifacts_stale_after_tuning_table_flip(
    tmp_path, monkeypatch
):
    """THE staleness hole the manifest's resolved knobs close: the config
    fingerprint hashes 'auto', so a `cli tune` run that flips a winner
    leaves the artifact DIR valid while the program an engine would
    compile today differs. Saved-then-flipped artifacts must refuse to
    load (fall back to compile), never serve the stale lowering."""
    cfg = make_micro_cfg()  # bn_stats_impl/pool_impl default 'auto'
    compiled = jax.jit(lambda x: x * 2.0).lower(
        jnp.zeros((2,), jnp.float32)
    ).compile()
    root = str(tmp_path)
    export.save_artifacts(cfg, root, "f32", False, [1], [1],
                          {"p": compiled})
    loaded = export.load_artifacts(cfg, root, "f32", False, [1], [1])
    assert loaded is not None and "p" in loaded
    # flip the tuned winners; fingerprint (and artifact dir) unchanged
    path = _diet_table(tmp_path, bn_stats_impl="twopass",
                       pool_impl="reduce_window")
    monkeypatch.setenv(autotune.TUNING_TABLE_ENV, path)
    autotune.clear_cache()
    assert export.artifact_dir_for(cfg, root, "f32", False) == \
        export.artifact_dir_for(cfg, root, "f32", False)
    assert export.load_artifacts(cfg, root, "f32", False, [1], [1]) is None
