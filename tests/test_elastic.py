"""Unit coverage for the elastic layer: the topology-invariant episode
schedule, the drain coordinator's file protocol, the bounded checkpoint
barriers, the sharded-store gather, and topology-changing resume through
the builder (the in-process halves of what ``test_elastic_e2e.py`` proves
across real process boundaries)."""

import json
import os
import signal

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.resilience import (
    DrainCoordinator,
    elastic,
    faults,
)


# -- the pure episode schedule -----------------------------------------------


def test_shard_slice_partitions_every_batch_exactly():
    for num_shards in (1, 2, 3, 6):
        slices = [elastic.shard_slice(6, s, num_shards)
                  for s in range(num_shards)]
        covered = [i for lo, hi in slices for i in range(lo, hi)]
        assert covered == list(range(6))  # block partition, order-preserving


def test_shard_slice_rejects_bad_topology():
    with pytest.raises(ValueError, match="re-partition"):
        elastic.shard_slice(6, 0, 4)
    with pytest.raises(ValueError, match="out of range"):
        elastic.shard_slice(6, 3, 3)


def test_process_for_index_inverts_shard_slice():
    for num_shards in (1, 2, 3):
        for g in range(18):
            p = elastic.process_for_index(g, 6, num_shards)
            lo, hi = elastic.shard_slice(6, p, num_shards)
            assert lo <= g % 6 < hi


def test_episode_cursor_is_pure_in_iteration():
    assert elastic.episode_cursor_for_iter(0, 6) == 0
    assert elastic.episode_cursor_for_iter(7, 6) == 42


# -- the drain coordinator's file protocol ------------------------------------


def _pair(tmp_path, margin=3):
    d = str(tmp_path / "elastic")
    return (
        DrainCoordinator(d, 0, 2, margin_iters=margin),
        DrainCoordinator(d, 1, 2, margin_iters=margin),
    )


def test_drain_request_commit_ack_roundtrip(tmp_path):
    primary, worker = _pair(tmp_path)
    # nothing published: polls are None on both sides
    assert primary.poll(3) is None and worker.poll(3) is None
    # the signalled (non-primary) worker publishes a request...
    assert worker.request_drain(signal.SIGTERM, 5) is True
    assert worker.request_drain(signal.SIGTERM, 5) is False  # idempotent
    assert worker.poll(5) is None  # only the primary can commit
    # ...the primary's next boundary poll promotes it to a commit
    commit = primary.poll(6)
    assert commit["drain_iter"] == 6 + 3
    assert commit["signal"] == signal.SIGTERM
    assert commit["requested_by"] == 1
    assert commit["requested_at_iter"] == 5
    # both sides refuse to drain before the agreed iteration...
    assert primary.should_drain(8) is None
    assert worker.poll(7) == commit  # observed through the filesystem
    assert worker.should_drain(8) is None
    # ...and drain exactly at it
    assert primary.should_drain(9) == commit
    assert worker.should_drain(9) == commit


def test_primary_own_signal_commits_directly(tmp_path):
    primary, worker = _pair(tmp_path, margin=2)
    primary.request_drain(signal.SIGINT, 4)
    commit = primary.poll(4)
    assert commit["drain_iter"] == 6 and commit["requested_by"] == 0
    assert worker.poll(5)["drain_iter"] == 6


def test_drain_overshoot_drains_immediately_with_warning(tmp_path, capsys):
    primary, worker = _pair(tmp_path, margin=1)
    primary.request_drain(signal.SIGTERM, 2)
    commit = primary.poll(2)  # drain_iter = 3
    # the worker first observes the commit PAST the agreed iteration
    assert worker.should_drain(5) == commit
    assert "overshot" in capsys.readouterr().err


def test_partial_commit_file_is_ignored_until_complete(tmp_path):
    primary, worker = _pair(tmp_path)
    os.makedirs(worker.coord_dir, exist_ok=True)
    with open(worker.commit_path, "w") as f:
        f.write('{"drain_iter": 9')  # torn write (no atomic rename used)
    assert worker.poll(4) is None


def test_stale_drain_files_never_preempt_a_resumed_run(tmp_path):
    """A consumed (or crash-stranded) drain from a previous incarnation of
    the experiment must not drain the resumed run: coordination files are
    run-tagged by the resume iteration, and a same-tag re-resume is swept
    by the primary's construction."""
    d = str(tmp_path / "elastic")
    old_primary = DrainCoordinator(d, 0, 2, run_tag="i0")
    old_worker = DrainCoordinator(d, 1, 2, run_tag="i0")
    old_worker.request_drain(signal.SIGTERM, 5)
    assert old_primary.poll(6) is not None  # committed, then the gang died
    # the resumed incarnation (from the iter-9 emergency) sees nothing
    new_primary = DrainCoordinator(d, 0, 2, run_tag="i9")
    new_worker = DrainCoordinator(d, 1, 2, run_tag="i9")
    assert new_primary.poll(9) is None
    assert new_worker.poll(9) is None
    # even a re-resume from the SAME iteration is safe: the primary's
    # construction sweeps its own tag's leftovers
    swept = DrainCoordinator(d, 0, 2, run_tag="i0")
    assert swept.poll(9) is None
    assert not os.path.exists(swept.commit_path)


def test_cached_stale_commit_dropped_when_sweep_wins(tmp_path):
    """A follower whose first poll cached a previous same-tag
    incarnation's commit BEFORE the primary's construction-time sweep must
    not drain on it: should_drain re-validates against the filesystem and
    forgets a commit whose file the sweep removed."""
    d = str(tmp_path / "elastic")
    old_primary = DrainCoordinator(d, 0, 2, run_tag="i0", margin_iters=1)
    old_primary.request_drain(signal.SIGTERM, 3)
    assert old_primary.poll(3) is not None  # stranded commit (gang died)
    # the re-resumed follower polls FIRST and caches the stale commit...
    follower = DrainCoordinator(d, 1, 2, run_tag="i0")
    assert follower.poll(4) is not None
    # ...then the primary's construction sweeps the leftovers
    DrainCoordinator(d, 0, 2, run_tag="i0")
    # drain time: the cached commit is re-validated and dropped
    assert follower.should_drain(9) is None
    assert follower.poll(9) is None  # cache cleared for good


def test_request_republished_after_primary_sweep(tmp_path):
    """A request that lost the race against the primary's construction-
    time sweep is re-asserted on the next boundary instead of silently
    dropped."""
    d = str(tmp_path / "elastic")
    worker = DrainCoordinator(d, 1, 2, run_tag="i0")
    worker.request_drain(signal.SIGTERM, 1)
    DrainCoordinator(d, 0, 2, run_tag="i0")  # ctor sweep eats the request
    assert not os.path.exists(worker.request_path)
    assert worker.request_drain(signal.SIGTERM, 2) is True  # re-published
    assert os.path.exists(worker.request_path)


def test_drain_poll_is_a_fault_site(tmp_path):
    primary, _ = _pair(tmp_path)
    faults.install("drain_poll:raise@call=2")
    try:
        primary.poll(1)
        with pytest.raises(RuntimeError, match="injected fault"):
            primary.poll(2)
    finally:
        faults.uninstall()


def test_new_fault_sites_validate_and_count():
    parsed = faults.parse_fault_spec(
        "barrier:oserror@call=1,drain_poll:raise@call=3x2"
    )
    assert [f.site for f in parsed] == ["barrier", "drain_poll"]
    with pytest.raises(ValueError, match="sigterm is only valid"):
        faults.parse_fault_spec("barrier:sigterm@call=1")


# -- bounded checkpoint barriers ---------------------------------------------


def test_barrier_timeout_error_names_phase_and_swap_path():
    from howtotrainyourmamlpytorch_tpu.experiment.checkpoint import (
        CheckpointBarrierTimeoutError,
    )

    err = CheckpointBarrierTimeoutError(
        "swap", "/exp/saved_models/train_model_7", 600.0,
        cause=TimeoutError("deadline"),
    )
    msg = str(err)
    assert "swap" in msg and "train_model_7" in msg
    assert "train_model_7.old" in msg and "train_model_7.tmp" in msg
    assert "ckpt_follower_timeout_s" in msg


def test_process_barrier_timeout_raises_diagnosable_error(monkeypatch):
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt

    class _Client:
        def wait_at_barrier(self, barrier_id, timeout_in_ms):
            raise RuntimeError("DEADLINE_EXCEEDED: barrier timed out")

    from jax._src import distributed as jax_distributed

    monkeypatch.setattr(jax_distributed.global_state, "client", _Client())
    with pytest.raises(
        ckpt.CheckpointBarrierTimeoutError, match="swap.*train_model_3"
    ):
        ckpt._process_barrier(
            "swap_train_model_3", "/exp/train_model_3", 0.01, phase="swap"
        )


def test_process_barrier_is_a_fault_site(monkeypatch):
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt

    seen = []

    class _Client:
        def wait_at_barrier(self, barrier_id, timeout_in_ms):
            seen.append((barrier_id, timeout_in_ms))

    from jax._src import distributed as jax_distributed

    monkeypatch.setattr(jax_distributed.global_state, "client", _Client())
    faults.install("barrier:oserror@call=2")
    try:
        ckpt._process_barrier("swap_x", "/exp/x", 5.0, phase="swap")
        with pytest.raises(OSError, match="injected fault"):
            ckpt._process_barrier("swap_x", "/exp/x", 5.0, phase="swap")
    finally:
        faults.uninstall()
    # unique per crossing + the configured bound in milliseconds
    assert seen == [("ckpt_swap_x_1", 5000)]


# -- loader: the global episode cursor + re-partition -------------------------


def _loader_cfg(data_root, cache_dir, **overrides):
    kwargs = dict(
        experiment_name="elastic_loader_probe",
        dataset_name="imagenet_synthetic_presplit",
        dataset_path=str(data_root),
        sets_are_pre_split=True,
        indexes_of_folders_indicating_class=[-3, -2],
        image_height=8, image_width=8, image_channels=3,
        num_classes_per_set=2, num_samples_per_class=1,
        num_target_samples=1, batch_size=6,
        total_iter_per_epoch=4, num_evaluation_tasks=6,
        num_dataprovider_workers=2,
        cache_dir=str(cache_dir), use_mmap_cache=True, seed=0,
    )
    kwargs.update(overrides)
    return MAMLConfig(**kwargs)


@pytest.fixture(scope="module")
def loader_env(tmp_path_factory):
    from test_resilience_e2e import _write_presplit_rgb

    root = tmp_path_factory.mktemp("elastic_loader")
    data_root = root / "imagenet_synthetic_presplit"
    _write_presplit_rgb(str(data_root))
    return str(data_root), str(root / "cache")


def _collect_batches(loader, n):
    out = []
    for i, b in enumerate(loader.get_train_batches(total_batches=n)):
        out.append([np.asarray(a) for a in b[:4]])
        if i + 1 == n:
            break
    return out


def test_sharded_loaders_reassemble_the_single_process_stream(loader_env):
    from howtotrainyourmamlpytorch_tpu.data.loader import (
        MetaLearningDataLoader,
    )

    data_root, cache_dir = loader_env
    cfg = _loader_cfg(data_root, cache_dir)
    whole = _collect_batches(
        MetaLearningDataLoader(cfg, 0, cache_dir, shard_id=0, num_shards=1),
        2,
    )
    for num_shards in (2, 3):
        shards = [
            _collect_batches(
                MetaLearningDataLoader(
                    cfg, 0, cache_dir, shard_id=s, num_shards=num_shards
                ),
                2,
            )
            for s in range(num_shards)
        ]
        for b in range(2):
            for part in range(4):
                reassembled = np.concatenate(
                    [shards[s][b][part] for s in range(num_shards)], axis=0
                )
                # block partition: process-major concatenation IS the
                # single-process global batch, bit for bit
                np.testing.assert_array_equal(
                    reassembled, whole[b][part]
                )


def test_mid_stream_cursor_resume_matches_uninterrupted(loader_env):
    from howtotrainyourmamlpytorch_tpu.data.loader import (
        MetaLearningDataLoader,
    )

    data_root, cache_dir = loader_env
    cfg = _loader_cfg(data_root, cache_dir)
    # uninterrupted single-shard stream: 4 batches
    whole = _collect_batches(
        MetaLearningDataLoader(cfg, 0, cache_dir, shard_id=0, num_shards=1),
        4,
    )
    # "kill" after 2 iterations, resume on THREE shards from the
    # checkpointed cursor: the tail of the stream re-partitions exactly
    cursor = elastic.episode_cursor_for_iter(2, cfg.global_tasks_per_batch)
    shards = [
        _collect_batches(
            MetaLearningDataLoader(
                cfg, current_iter=2, cache_dir=cache_dir,
                shard_id=s, num_shards=3, episode_cursor=cursor,
            ),
            2,
        )
        for s in range(3)
    ]
    for b in range(2):
        for part in range(4):
            reassembled = np.concatenate(
                [shards[s][b][part] for s in range(3)], axis=0
            )
            np.testing.assert_array_equal(reassembled, whole[2 + b][part])


def test_cursor_mismatch_names_the_batch_size_drift(loader_env):
    from howtotrainyourmamlpytorch_tpu.data.loader import (
        MetaLearningDataLoader,
    )

    data_root, cache_dir = loader_env
    cfg = _loader_cfg(data_root, cache_dir)
    with pytest.raises(ValueError, match="episode cursor"):
        MetaLearningDataLoader(
            cfg, current_iter=2, cache_dir=cache_dir,
            shard_id=0, num_shards=1,
            episode_cursor=5,  # != 2 * 6
        )


def test_indivisible_elastic_topology_fails_loudly(loader_env):
    from howtotrainyourmamlpytorch_tpu.data.loader import (
        MetaLearningDataLoader,
    )

    data_root, cache_dir = loader_env
    cfg = _loader_cfg(data_root, cache_dir)
    with pytest.raises(ValueError, match="re-partition"):
        MetaLearningDataLoader(
            cfg, 0, cache_dir, shard_id=0, num_shards=4
        )


# -- topology-changing resume through the builder (satellite: peek/latest) ----


@pytest.mark.slow
def test_resume_prefers_newer_emergency_and_records_topology_change(
    loader_env, tmp_path,
):
    """A checkpoint gang of 4 processes wrote `latest` (iter 4) and a NEWER
    preemption emergency (iter 6); resuming on THIS single process must
    pick the emergency (peek compares iters without a restore), consume
    its episode cursor, and emit the elastic resume record old=4 -> new=1."""
    import jax

    from howtotrainyourmamlpytorch_tpu.core import maml
    from howtotrainyourmamlpytorch_tpu.data.loader import (
        MetaLearningDataLoader,
    )
    from howtotrainyourmamlpytorch_tpu.experiment import checkpoint as ckpt
    from howtotrainyourmamlpytorch_tpu.experiment.builder import (
        ExperimentBuilder,
    )
    from howtotrainyourmamlpytorch_tpu.experiment.system import (
        MAMLFewShotClassifier,
    )

    data_root, cache_dir = loader_env
    exp_root = str(tmp_path)
    cfg = _loader_cfg(
        data_root, cache_dir,
        experiment_name=os.path.join(exp_root, "topo_resume"),
        total_epochs=2, telemetry_level="scalars",
        compilation_cache_dir="",
        total_epochs_before_pause=100,
    )
    state = maml.init_state(cfg)
    saved = os.path.join(exp_root, "topo_resume", "saved_models")
    os.makedirs(saved, exist_ok=True)
    tpb = cfg.global_tasks_per_batch
    base = {"best_val_acc": 0.0, "best_val_iter": 0,
            "per_epoch_statistics": {"val_accuracy_mean": [0.5]}}
    ckpt.save_checkpoint(
        saved, "train_model", "latest", state,
        {**base, "current_iter": 4, "process_count": 4,
         "episode_cursor": 4 * tpb},
    )
    ckpt.save_checkpoint(
        saved, "train_model", "emergency", state,
        {**base, "current_iter": 6, "process_count": 4,
         "episode_cursor": 6 * tpb, "emergency_reason": "preemption",
         "preempt_signal": int(signal.SIGTERM)},
    )
    # peek is enough to rank the candidates — no array restore
    assert ckpt.peek_experiment_state(
        saved, "train_model", "emergency"
    )["process_count"] == 4

    model = MAMLFewShotClassifier(cfg, use_mesh=False)
    builder = ExperimentBuilder(
        cfg, model, MetaLearningDataLoader,
        experiment_root=exp_root, verbose=False,
    )
    assert builder.state["current_iter"] == 6  # the newer emergency won
    assert builder.data.total_train_iters_produced == 6 * tpb
    builder.telemetry.close()

    records = []
    log = os.path.join(exp_root, "topo_resume", "logs", "telemetry.jsonl")
    with open(log) as f:
        records = [json.loads(line) for line in f if line.strip()]
    (resume,) = [
        r for r in records
        if r["kind"] == "elastic" and r["event"] == "resume"
    ]
    assert resume["old_process_count"] == 4
    assert resume["new_process_count"] == jax.process_count() == 1
    assert resume["iter"] == 6
    assert resume["episode_cursor"] == 6 * tpb


# -- sharded resident stores --------------------------------------------------


def _store_cfg(**overrides):
    kwargs = dict(
        dataset_name="imagenet_sharded_probe",
        use_mmap_cache=True,
        data_placement="device",
        store_sharding="hosts",
        image_height=6, image_width=6, image_channels=3,
        num_classes_per_set=2, num_samples_per_class=1,
        num_target_samples=1, batch_size=8,
        cnn_num_filters=4, num_stages=1, max_pooling=True,
        number_of_training_steps_per_iter=1,
        number_of_evaluation_steps_per_iter=1,
        use_remat=False, seed=0,
    )
    kwargs.update(overrides)
    return MAMLConfig(**kwargs)


def test_pad_store_rows_only_when_needed():
    from howtotrainyourmamlpytorch_tpu.ops.device_pipeline import (
        pad_store_rows,
    )

    store = np.arange(10 * 2, dtype=np.uint8).reshape(10, 2)
    assert pad_store_rows(store, 2) is store
    padded = pad_store_rows(store, 4)
    assert padded.shape == (12, 2)
    np.testing.assert_array_equal(padded[:10], store)
    assert not padded[10:].any()


@pytest.fixture(scope="module")
def hybrid_mesh():
    import jax

    from howtotrainyourmamlpytorch_tpu.parallel import distributed

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    return distributed.hybrid_task_mesh(processes=2)


@pytest.mark.slow
def test_sharded_store_gather_bit_exact_and_batch_sized_collectives(
    hybrid_mesh,
):
    """The masked-gather + hosts-psum expansion must reproduce the
    replicated gather bit-for-bit (exactly one shard contributes per row)
    while its collectives stay BATCH-sized float32 — never store-sized,
    never uint8 (the PR 8 SPMD residency invariants)."""
    import jax

    from howtotrainyourmamlpytorch_tpu.analysis import contracts
    from howtotrainyourmamlpytorch_tpu.ops import device_pipeline as dp
    from howtotrainyourmamlpytorch_tpu.parallel import distributed

    cfg = _store_cfg()
    rng = np.random.RandomState(0)
    # store >> batch so "batch-sized" and "store-sized" are distinguishable
    store = rng.randint(0, 256, (4096, 6, 6, 3), dtype=np.uint8)
    gather = rng.randint(0, 4096, (8, 2, 2)).astype(np.int32)
    rot_k = np.zeros((8, 2), np.int32)

    expand_rep = dp.make_index_expander(cfg, augment=False)
    expand_sh = dp.make_index_expander(
        cfg, augment=False, store_mesh=hybrid_mesh
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    store_rep = jax.device_put(store, NamedSharding(hybrid_mesh, P()))
    store_sh = jax.device_put(
        dp.pad_store_rows(store, 2),
        distributed.store_row_sharding(hybrid_mesh),
    )
    batch_sharding = distributed.global_batch_sharding(hybrid_mesh)
    g = jax.device_put(gather, batch_sharding)
    rk = jax.device_put(rot_k, batch_sharding)

    out_rep = jax.jit(expand_rep)(store_rep, g, rk)
    out_sh = jax.jit(expand_sh)(store_sh, g, rk)
    for a, b in zip(out_rep, out_sh):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    hlo = jax.jit(expand_sh).lower(store_sh, g, rk).compile().as_text()
    colls = contracts.collective_instructions(hlo)
    assert colls, "sharded gather must use a hosts-axis collective"
    batch_bytes = 8 * 2 * 2 * 6 * 6 * 3 * 4  # decoded f32 batch
    for c in colls:
        assert c["bytes"] <= batch_bytes, c
        assert c["bytes"] < store.nbytes // 4, c
        assert "u8[" not in c["shape"], f"uint8 pixels crossed the mesh: {c}"


@pytest.mark.slow
def test_system_facade_places_and_gathers_sharded_stores(hybrid_mesh):
    """store_sharding='hosts' through MAMLFewShotClassifier: the resident
    store lands row-sharded over the hosts axis, the indexed eval runs,
    and per-task predictions equal the replicated-store run's exactly."""
    import jax

    from howtotrainyourmamlpytorch_tpu.data.loader import IndexBatch
    from howtotrainyourmamlpytorch_tpu.experiment.system import (
        MAMLFewShotClassifier,
    )
    from howtotrainyourmamlpytorch_tpu.parallel import (
        distributed,
        mesh as mesh_lib,
    )

    rng = np.random.RandomState(1)
    store = rng.randint(0, 256, (64, 6, 6, 3), dtype=np.uint8)
    batch = IndexBatch(
        gather=rng.randint(0, 64, (8, 2, 2)).astype(np.int32),
        rot_k=np.zeros((8, 2), np.int32),
        seeds=np.arange(8, dtype=np.int64),
        set_name="val",
        augment=False,
    )

    def build(sharding):
        model = MAMLFewShotClassifier(
            _store_cfg(store_sharding=sharding), use_mesh=False
        )
        # simulate the pod's hybrid mesh on one process (tests' standard
        # trick — distributed.hybrid_task_mesh(processes=2)), then
        # re-resolve the sharding decision against it
        model.mesh = hybrid_mesh
        model.state = mesh_lib.replicate_state(hybrid_mesh, model.state)
        model._resolve_store_sharding()
        model.register_flat_stores({"val": store})
        return model

    sharded = build("hosts")
    assert sharded._store_mesh is hybrid_mesh
    m_sh, p_sh = sharded.run_validation_iter(batch, return_preds=True)
    arr = sharded._device_stores["val"]
    assert arr.sharding.spec == distributed.store_row_sharding(
        hybrid_mesh
    ).spec
    # each device holds 1/2 of the rows (sharded over hosts, replicated
    # over its row's task axis)
    assert arr.addressable_shards[0].data.shape[0] == store.shape[0] // 2

    replicated = build("replicated")
    assert replicated._store_mesh is None
    m_rep, p_rep = replicated.run_validation_iter(batch, return_preds=True)
    # the GATHER itself is bit-exact (proved at the expander level above);
    # through the whole eval step this simulated-mesh harness compares a
    # 4-way-sharded compute (the replicated arm's 1-D index sharding) with
    # an 8-way one (the sharded arm's batch constraint), so downstream conv
    # tiling may differ in the last ULP — real multihost runs shard both
    # arms identically (global_batch_sharding) and keep bit-identity
    np.testing.assert_allclose(p_sh, p_rep, rtol=1e-6, atol=1e-7)
    for key in m_rep:
        np.testing.assert_allclose(
            np.asarray(m_sh[key]), np.asarray(m_rep[key]),
            rtol=1e-6, atol=1e-6, err_msg=key,
        )


def test_store_sharding_degrades_to_replicated_off_hybrid_mesh(capsys):
    """A single-host (1-D task) mesh has no host axis: the knob degrades
    to replication with a log line instead of mis-sharding."""
    from howtotrainyourmamlpytorch_tpu.experiment.system import (
        MAMLFewShotClassifier,
    )

    model = MAMLFewShotClassifier(_store_cfg(), use_mesh=True)
    assert model._store_mesh is None
    assert "stay replicated" in capsys.readouterr().out
