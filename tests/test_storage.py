"""Metric storage: atomic JSON writes and resume-safe CSV loading."""

import json
import os

import pytest

from howtotrainyourmamlpytorch_tpu.utils import storage


def test_save_to_json_round_trip(tmp_path):
    path = str(tmp_path / "summary_statistics.json")
    storage.save_to_json(path, {"val_accuracy_mean": [0.5, 0.75]})
    assert storage.load_from_json(path) == {"val_accuracy_mean": [0.5, 0.75]}
    assert not os.path.exists(path + ".tmp")


def test_save_to_json_crash_mid_write_keeps_old_file(tmp_path, monkeypatch):
    """A crash while serializing must leave the previous complete file in
    place (tmp + os.replace), never a truncated one that breaks resume."""
    path = str(tmp_path / "summary_statistics.json")
    storage.save_to_json(path, {"epoch": [1]})

    def boom(*args, **kwargs):
        raise RuntimeError("simulated crash mid-serialization")

    monkeypatch.setattr(storage.json, "dump", boom)
    with pytest.raises(RuntimeError, match="simulated crash"):
        storage.save_to_json(path, {"epoch": [1, 2]})
    monkeypatch.undo()
    # the original file is intact and valid JSON
    assert storage.load_from_json(path) == {"epoch": [1]}
    storage.save_to_json(path, {"epoch": [1, 2]})
    assert storage.load_from_json(path) == {"epoch": [1, 2]}


def test_load_statistics_round_trip(tmp_path):
    storage.save_statistics(str(tmp_path), ["a", "b"], create=True)
    storage.save_statistics(str(tmp_path), [1, 2])
    data = storage.load_statistics(str(tmp_path))
    assert data == {"a": ["1"], "b": ["2"]}


def test_load_statistics_empty_csv_raises_clear_error(tmp_path):
    """An empty/headerless stats CSV (crash-truncated) must raise a named
    error, not the reference's bare IndexError on rows[0]."""
    open(os.path.join(str(tmp_path), "summary_statistics.csv"), "w").close()
    with pytest.raises(ValueError, match="empty or has no header"):
        storage.load_statistics(str(tmp_path))


def test_save_to_json_overwrites_corrupt_file(tmp_path):
    """Recovery path: a pre-atomicity corrupted file is simply replaced by
    the next complete write."""
    path = str(tmp_path / "summary_statistics.json")
    with open(path, "w") as f:
        f.write('{"epoch": [1, 2')  # truncated JSON
    with pytest.raises(json.JSONDecodeError):
        storage.load_from_json(path)
    storage.save_to_json(path, {"epoch": [3]})
    assert storage.load_from_json(path) == {"epoch": [3]}
