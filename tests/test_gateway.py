"""Fleet gateway tests (serving/gateway.py + serving/fleet.py).

The front tier is stdlib + numpy by design, so everything here runs
real HTTP over loopback sockets against ``FleetHost`` instances backed
by STUB routers/pools — the full wire path (encode -> gateway ->
admission -> forward -> host decode -> re-stamp -> frame -> merge)
without an engine in sight. The jax-heavy end-to-end shape lives in the
CI ``fleet-smoke`` job (serve-bench ``--fleet``), not here.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from howtotrainyourmamlpytorch_tpu.config import MAMLConfig
from howtotrainyourmamlpytorch_tpu.serving import gateway as gw
from howtotrainyourmamlpytorch_tpu.serving.batcher import (
    AdaptRequest,
    IndexRequest,
)
from howtotrainyourmamlpytorch_tpu.serving.fleet import FleetHost
from howtotrainyourmamlpytorch_tpu.serving.metrics import LogHistogram
from howtotrainyourmamlpytorch_tpu.serving.router import (
    request_fingerprint,
)


# -- stubs -------------------------------------------------------------------


class _ListSink:
    def __init__(self):
        self.records = []

    def write(self, record):
        self.records.append(record)

    def close(self):
        pass


class _FakeResult:
    def __init__(self, tenant_id="t0", way=3, targets=2):
        self.tenant_id = tenant_id
        self.preds = np.arange(
            way * targets * 5, dtype=np.float32
        ).reshape(way * targets, 5)
        self.loss = 0.25
        self.accuracy = 0.875


class _StubPending:
    def __init__(self, result):
        self._result = result

    def get(self, timeout=None):
        if isinstance(self._result, BaseException):
            raise self._result
        return self._result


class _StubRouter:
    """Captures submissions; returns a canned result per request."""

    def __init__(self):
        self.submitted = []

    def submit(self, request):
        self.submitted.append(request)
        return _StubPending(_FakeResult(request.tenant_id or "t0"))

    def stats(self):
        return {"submitted": len(self.submitted)}


class _StubReplica:
    def __init__(self, depth=0):
        self._depth = depth

    def queue_depth(self):
        return self._depth


class _StubPool:
    def __init__(self, depth=0, hist_values=()):
        self.replicas = [_StubReplica(depth)]
        self._hist_values = hist_values

    def readiness(self):
        return {0: True}

    def rollup(self):
        adapt = LogHistogram()
        queue = LogHistogram()
        for v in self._hist_values:
            adapt.observe(v)
            queue.observe(v * 2.0)
        return {
            "dispatches": len(self._hist_values),
            "tenants": len(self._hist_values),
            "adapt_ms_hist": adapt.to_dict(),
            "queue_ms_hist": queue.to_dict(),
        }


def _gw_cfg(**kw):
    kw.setdefault("serving_gateway_health_interval_s", 0.05)
    return MAMLConfig(**kw)


def _adapt_request(seed=123, dtype=np.float32, **kw):
    rng = np.random.RandomState(seed)
    x = rng.randn(3, 1, 10, 10, 1)
    q = rng.randn(3, 2, 10, 10, 1)
    if dtype == np.uint8:
        x = (x * 32 + 128).clip(0, 255)
        q = (q * 32 + 128).clip(0, 255)
    return AdaptRequest(
        support_x=x.astype(dtype),
        support_y=np.tile(np.arange(3, dtype=np.int32)[:, None], (1, 1)),
        query_x=q.astype(dtype),
        query_y=None,
        **kw,
    )


def _make_fleet(n=2, depth=0, sink=None, **cfg_kw):
    """n stub-backed FleetHosts behind one Gateway (manual polling)."""
    hosts, routers = {}, {}
    members = {}
    for i in range(n):
        router = _StubRouter()
        host = FleetHost(
            router, _StubPool(depth=depth), host_id=f"host{i:02d}"
        )
        hosts[host.host_id] = host
        routers[host.host_id] = router
        members[host.host_id] = f"127.0.0.1:{host.port}"
    gateway = gw.Gateway(
        _gw_cfg(**cfg_kw), members, sink=sink, start_health_loop=False
    )
    gateway.poll_once()
    return gateway, hosts, routers


def _close_fleet(gateway, hosts):
    gateway.close()
    for h in hosts.values():
        h.close()


# -- wire codec --------------------------------------------------------------


def test_wire_adapt_round_trip_preserves_arrays_and_header():
    req = _adapt_request(tenant_id="tenant-7", deadline_ms=50.0)
    req.priority = 1
    frame = gw.encode_request(req)
    back, header = gw.decode_request(frame)
    assert isinstance(back, AdaptRequest)
    assert header["kind"] == "adapt" and header["priority"] == 1
    assert back.tenant_id == "tenant-7" and back.deadline_ms == 50.0
    np.testing.assert_array_equal(back.support_x, req.support_x)
    np.testing.assert_array_equal(back.support_y, req.support_y)
    np.testing.assert_array_equal(back.query_x, req.query_x)
    assert back.query_y is None
    # the decoded arrays are writable copies, not views pinning the body
    back.support_x[0, 0, 0, 0, 0] = 42.0


def test_wire_inherits_ingest_compression():
    """The PR-13 ingest encodings apply ON THE WIRE: a uint8 frame is
    ~4x smaller than its f32 twin, and an index request against a
    fleet-resident store is under 1KB."""
    f32 = len(gw.encode_request(_adapt_request(dtype=np.float32)))
    u8 = len(gw.encode_request(_adapt_request(dtype=np.uint8)))
    assert u8 * 3 < f32
    idx = IndexRequest(
        support_idx=np.arange(3, dtype=np.int64)[:, None],
        query_idx=np.arange(6, dtype=np.int64).reshape(3, 2),
        tenant_id="tenant-9",
    )
    frame = gw.encode_request(idx)
    assert len(frame) < 1024
    back, header = gw.decode_request(frame)
    assert isinstance(back, IndexRequest) and header["kind"] == "index"
    assert back.labeled is True
    assert back.support_idx.dtype == np.int32  # wire narrows to int32
    np.testing.assert_array_equal(
        back.support_idx, idx.support_idx.astype(np.int32)
    )


def test_wire_fingerprint_survives_the_codec():
    """Routing identity can't drift across the network: the decoded
    request hashes to the SAME affinity fingerprint the client's
    original did (same digest recipe end to end)."""
    for req in (
        _adapt_request(),
        _adapt_request(dtype=np.uint8),
        IndexRequest(
            support_idx=np.arange(3, dtype=np.int32)[:, None],
            query_idx=np.arange(6, dtype=np.int32).reshape(3, 2),
        ),
    ):
        back, _ = gw.decode_request(gw.encode_request(req))
        assert request_fingerprint(back) == request_fingerprint(req)


def test_wire_result_round_trip():
    result = _FakeResult("tenant-3")
    frame = gw.encode_result(result, host_id="host01", host_ms=4.25)
    out = gw.decode_result(frame)
    assert out["ok"] is True and out["tenant_id"] == "tenant-3"
    assert out["loss"] == 0.25 and out["accuracy"] == 0.875
    assert out["host_id"] == "host01" and out["host_ms"] == 4.25
    np.testing.assert_array_equal(out["preds"], result.preds)


def test_wire_malformed_frames_raise_typed_errors():
    frame = gw.encode_request(_adapt_request())
    with pytest.raises(gw.WireError, match="truncated"):
        gw.decode_request(frame[:2])
    with pytest.raises(gw.WireError, match="truncated"):
        gw.decode_request(frame[:-10])  # short buffers
    with pytest.raises(gw.WireError, match="not valid JSON"):
        gw.decode_request(b"\x00\x00\x00\x04aaaa")
    bad_kind = gw._encode_frame({"kind": "mystery", "arrays": []}, [])
    with pytest.raises(gw.WireError, match="adapt.*index"):
        gw.decode_request(bad_kind)


# -- the consistent-hash host ring -------------------------------------------


def test_home_host_is_sorted_ring_modular_arithmetic():
    fp = request_fingerprint(_adapt_request())
    hosts = ["host02", "host00", "host01"]
    assert gw.home_host(fp, hosts) == gw.home_host(fp, sorted(hosts))
    assert gw.home_host(fp, hosts) in hosts
    # a single-host fleet is degenerate but legal
    assert gw.home_host(fp, ["only"]) == "only"


def test_home_host_stable_across_process_restarts():
    """The fleet-level twin of the router's fingerprint-stability test:
    (fingerprint -> home host) is a pure function of the content digest
    and the membership set — two fresh interpreters with different
    PYTHONHASHSEEDs must agree with this process bit-for-bit (the
    adapted-params cache key survives a gateway restart)."""
    script = (
        "import numpy as np\n"
        "from howtotrainyourmamlpytorch_tpu.serving.gateway import (\n"
        "    home_host)\n"
        "from howtotrainyourmamlpytorch_tpu.serving.router import (\n"
        "    request_fingerprint)\n"
        "from howtotrainyourmamlpytorch_tpu.serving.batcher import (\n"
        "    AdaptRequest, IndexRequest)\n"
        "rng = np.random.RandomState(123)\n"
        "req = AdaptRequest(\n"
        "    support_x=rng.randn(3, 1, 10, 10, 1).astype(np.float32),\n"
        "    support_y=np.tile(\n"
        "        np.arange(3, dtype=np.int32)[:, None], (1, 1)),\n"
        "    query_x=rng.randn(3, 2, 10, 10, 1).astype(np.float32),\n"
        "    query_y=None)\n"
        "idx = IndexRequest(\n"
        "    support_idx=np.arange(3, dtype=np.int64)[:, None],\n"
        "    query_idx=np.arange(6, dtype=np.int64).reshape(3, 2))\n"
        "ring = ['host02', 'host00', 'host03', 'host01']\n"
        "for r in (req, idx):\n"
        "    fp = request_fingerprint(r)\n"
        "    print(fp, home_host(fp, ring))\n"
    )
    outs = []
    for seed in ("0", "424242"):
        env = dict(os.environ, PYTHONHASHSEED=seed, JAX_PLATFORMS="cpu")
        outs.append(subprocess.run(
            [sys.executable, "-c", script], env=env, text=True,
            capture_output=True, check=True, timeout=120,
        ).stdout)
    assert outs[0] == outs[1]
    # ... and with THIS process (a third interpreter lifetime)
    ring = ["host02", "host00", "host03", "host01"]
    rng = np.random.RandomState(123)
    req = AdaptRequest(
        support_x=rng.randn(3, 1, 10, 10, 1).astype(np.float32),
        support_y=np.tile(np.arange(3, dtype=np.int32)[:, None], (1, 1)),
        query_x=rng.randn(3, 2, 10, 10, 1).astype(np.float32),
        query_y=None,
    )
    idx = IndexRequest(
        support_idx=np.arange(3, dtype=np.int64)[:, None],
        query_idx=np.arange(6, dtype=np.int64).reshape(3, 2),
    )
    lines = [
        f"{request_fingerprint(r)} "
        f"{gw.home_host(request_fingerprint(r), ring)}"
        for r in (req, idx)
    ]
    assert outs[0] == "\n".join(lines) + "\n"


# -- end to end over real sockets --------------------------------------------


def test_gateway_serves_end_to_end_and_stamps_edge_fields():
    """Client frame -> gateway -> host -> framed result: the reply
    decodes, the host saw the gateway-stamped fields (clamped priority,
    remaining deadline, wire-elapsed gateway_ms), and the admission
    counters moved."""
    gateway, hosts, routers = _make_fleet(n=2)
    server = gw.GatewayServer(gateway)
    try:
        client = gw.GatewayClient(f"127.0.0.1:{server.port}")
        req = _adapt_request(tenant_id="tenant-1", deadline_ms=500.0)
        req.priority = 99  # clamped into the tier range at the edge
        reply = client.serve(req)
        assert reply.ok and reply.status == 200
        assert reply.result["tenant_id"] == "tenant-1"
        assert reply.result["host_id"] in hosts
        np.testing.assert_array_equal(
            reply.result["preds"], _FakeResult("tenant-1").preds
        )
        seen = [r for rt in routers.values() for r in rt.submitted]
        assert len(seen) == 1
        assert seen[0].priority == gateway.priority_tiers - 1
        assert seen[0].gateway_ms is not None
        # only DURATIONS cross the wire: the host-side deadline is the
        # REMAINING budget, strictly under the client's original
        assert 0 < seen[0].deadline_ms < 500.0
        assert (
            pytest.approx(500.0 - seen[0].gateway_ms)
            == seen[0].deadline_ms
        )
        assert gateway.admitted == 1
        # affinity: the home host actually served it
        fp = request_fingerprint(req)
        assert reply.result["host_id"] == gw.home_host(fp, list(hosts))
    finally:
        server.close()
        _close_fleet(gateway, hosts)


def test_gateway_bad_frame_is_typed_400():
    gateway, hosts, _ = _make_fleet(n=1)
    try:
        status, ctype, body = gateway.handle_serve(b"garbage")
        assert status == 400 and ctype == "application/json"
        assert json.loads(body)["error"] == "bad_request"
    finally:
        _close_fleet(gateway, hosts)


# -- admission control + deadline shedding -----------------------------------


def test_admission_shed_is_typed_and_recorded():
    """A request whose home host's load estimate is at the budget is
    rejected AT THE EDGE: HTTP 429, reason='admission', one gateway
    shed record, nothing forwarded."""
    sink = _ListSink()
    gateway, hosts, routers = _make_fleet(
        n=1, depth=4, sink=sink, serving_gateway_queue_budget=4
    )
    try:
        status, _, body = gateway.handle_serve(
            gw.encode_request(_adapt_request(tenant_id="t-shed"))
        )
        assert status == 429
        err = json.loads(body)
        assert err["error"] == "shed" and err["reason"] == "admission"
        assert err["load"] == 4 and err["budget"] == 4
        assert gateway.shed["admission"] == 1 and gateway.admitted == 0
        assert not any(rt.submitted for rt in routers.values())
        recs = [r for r in sink.records if r.get("event") == "shed"]
        assert len(recs) == 1 and recs[0]["kind"] == "gateway"
        assert recs[0]["reason"] == "admission"
        assert recs[0]["tenant_id"] == "t-shed"
    finally:
        _close_fleet(gateway, hosts)


def test_priority_tiers_shrink_the_admission_budget():
    """Tier 0 keeps the full budget; tier k gets budget >> k — the same
    load admits a tier-0 request and sheds a tier-2 one."""
    gateway, hosts, routers = _make_fleet(
        n=1, depth=5, serving_gateway_queue_budget=8,
        serving_gateway_priority_tiers=3,
    )
    try:
        lo = _adapt_request(tenant_id="t-lo")
        status, _, _ = gateway.handle_serve(gw.encode_request(lo))
        assert status == 200  # load 5 < budget 8
        hi = _adapt_request(tenant_id="t-hi")
        hi.priority = 2
        status, _, body = gateway.handle_serve(gw.encode_request(hi))
        assert status == 429  # load 5 >= 8 >> 2 == 2
        assert json.loads(body)["budget"] == 2
    finally:
        _close_fleet(gateway, hosts)


def test_deadline_shed_against_queue_estimate():
    """A deadline the home host's queue estimate (load x service-time
    EWMA) already exceeds is shed typed instead of queued to die."""
    sink = _ListSink()
    gateway, hosts, _ = _make_fleet(
        n=1, sink=sink, serving_gateway_queue_budget=1024
    )
    try:
        # establish the EWMA with one served request...
        status, _, _ = gateway.handle_serve(
            gw.encode_request(_adapt_request(tenant_id="t-warm"))
        )
        assert status == 200
        h = gateway.ring[0]
        assert h.ewma_ms is not None and h.ewma_ms > 0
        # ...then pile up a queue and ask for the impossible
        hosts[h.host_id].pool.replicas[0]._depth = 500
        gateway.poll_once()
        doomed = _adapt_request(tenant_id="t-doomed", deadline_ms=0.001)
        status, _, body = gateway.handle_serve(gw.encode_request(doomed))
        assert status == 429
        err = json.loads(body)
        assert err["reason"] == "deadline"
        assert err["queue_est_ms"] >= 0.001
        assert gateway.shed == {"admission": 0, "deadline": 1}
        recs = [r for r in sink.records if r.get("event") == "shed"]
        assert recs and recs[-1]["reason"] == "deadline"
    finally:
        _close_fleet(gateway, hosts)


def test_host_sheds_when_edge_spent_the_whole_budget():
    """The host-side backstop: a frame arriving with its deadline
    budget already consumed by the edge (gateway_elapsed_ms >=
    deadline_ms — the shed estimate raced a slow forward) is refused
    429 at the HOST, never queued."""
    router = _StubRouter()
    host = FleetHost(router, _StubPool())
    try:
        frame = gw.encode_request(
            _adapt_request(tenant_id="t-late", deadline_ms=10.0)
        )
        header, blob = gw._decode_frame(frame)
        header["gateway_elapsed_ms"] = 50.0
        status, _, body = host.handle_serve(
            gw._encode_frame(header, [blob])
        )
        assert status == 429
        err = json.loads(body)
        assert err["reason"] == "deadline" and err["where"] == "host"
        assert not router.submitted
    finally:
        host.close()


# -- host loss: trip, re-home, fail fast -------------------------------------


def test_host_death_between_sweeps_rehomes_in_flight_request():
    """The satellite-2 regression: a host dying BETWEEN health sweeps
    is caught at forward time — the socket failure trips it (one
    rehome record, root cause chained) and the SAME request is retried
    on its deterministic re-home, so zero admitted requests drop."""
    sink = _ListSink()
    gateway, hosts, routers = _make_fleet(n=3, sink=sink)
    try:
        req = _adapt_request(tenant_id="t-survivor", deadline_ms=800.0)
        fp = request_fingerprint(req)
        home = gw.home_host(fp, list(hosts))
        # kill the home WITHOUT a health sweep noticing
        hosts[home].close()
        status, ctype, body = gateway.handle_serve(
            gw.encode_request(req)
        )
        assert status == 200 and ctype == gw.WIRE_CONTENT_TYPE
        served_by = gw.decode_result(body)["host_id"]
        assert served_by != home
        # deterministic re-home: the next ready host on the FIXED ring
        ring_ids = [h.host_id for h in gateway.ring]
        expect = ring_ids[
            (ring_ids.index(home) + 1) % len(ring_ids)
        ]
        assert served_by == expect
        assert gateway.rehomes == 1 and gateway.forward_failures == 1
        dead = next(h for h in gateway.ring if h.host_id == home)
        assert dead.tripped and dead.trip_cause is not None
        recs = [r for r in sink.records if r.get("event") == "rehome"]
        assert len(recs) == 1 and recs[0]["host"] == home
        assert "ConnectionRefused" in recs[0]["cause"]
        # healthy homes never reshuffle: a request homed on a live host
        # still lands there after the trip
        for _ in range(8):
            other = _adapt_request(
                seed=np.random.randint(1 << 30), tenant_id="t-other"
            )
            ofp = request_fingerprint(other)
            if gw.home_host(ofp, list(hosts)) != home:
                status, _, body = gateway.handle_serve(
                    gw.encode_request(other)
                )
                assert status == 200
                assert gw.decode_result(body)["host_id"] == gw.home_host(
                    ofp, list(hosts)
                )
                break
    finally:
        _close_fleet(gateway, hosts)


def test_all_hosts_down_is_immediate_typed_503_with_chained_causes():
    """No ready host left: the request fails IMMEDIATELY (no socket
    hang) with the typed host_down body chaining every forward
    failure's root cause — the batcher worker-crash semantics at the
    network layer."""
    gateway, hosts, _ = _make_fleet(n=2)
    try:
        for h in hosts.values():
            h.close()
        status, _, body = gateway.handle_serve(
            gw.encode_request(_adapt_request(tenant_id="t-doomed"))
        )
        assert status == 503
        err = json.loads(body)
        assert err["error"] == "host_down"
        assert err["cause"] and "ConnectionRefused" in err["cause"]
        assert len(err["causes"]) == 2  # both hosts' failures chained
        assert gateway.rehomes == 2
    finally:
        _close_fleet(gateway, hosts)


def test_poll_once_trips_only_previously_ready_hosts():
    """The PR-15 trip gate, lifted: a host that NEVER came up is
    skipped (still warming), not tripped; one that answered ready and
    then vanished is latched out with exactly one rehome record."""
    sink = _ListSink()
    router = _StubRouter()
    live = FleetHost(router, _StubPool(), host_id="host00")
    members = {
        "host00": f"127.0.0.1:{live.port}",
        # a port nothing listens on: never ready, never tripped
        "host01": "127.0.0.1:1",
    }
    gateway = gw.Gateway(
        _gw_cfg(), members, sink=sink, start_health_loop=False
    )
    try:
        gateway.poll_once()
        h0, h1 = gateway.ring
        assert h0.ready and not h1.ready and not h1.tripped
        live.close()
        gateway.poll_once()
        gateway.poll_once()  # a second sweep must not double-trip
        assert h0.tripped and not h1.tripped
        assert gateway.rehomes == 1
        assert sum(
            1 for r in sink.records if r.get("event") == "rehome"
        ) == 1
    finally:
        gateway.close()


# -- fleet rollup: exact histogram merge -------------------------------------


def test_fleet_rollup_merges_histograms_exactly():
    """Fleet p99 comes from ONE merged histogram family, not averaged
    percentiles: the gateway rollup over two hosts' rollup payloads
    equals a single histogram that observed every value (the PR-17
    merge contract, across process boundaries)."""
    values_a = [1.0, 2.0, 3.0, 40.0]
    values_b = [0.5, 2.0, 800.0]
    router_a, router_b = _StubRouter(), _StubRouter()
    host_a = FleetHost(
        router_a, _StubPool(hist_values=values_a), host_id="host00"
    )
    host_b = FleetHost(
        router_b, _StubPool(hist_values=values_b), host_id="host01"
    )
    sink = _ListSink()
    gateway = gw.Gateway(
        _gw_cfg(),
        {
            "host00": f"127.0.0.1:{host_a.port}",
            "host01": f"127.0.0.1:{host_b.port}",
        },
        sink=sink, start_health_loop=False,
    )
    try:
        gateway.poll_once()
        out = gateway.rollup()
        truth = LogHistogram()
        for v in values_a + values_b:
            truth.observe(v)
        merged = LogHistogram.from_dict(out["adapt_ms_hist"])
        assert merged.counts == truth.counts
        assert merged.count == truth.count
        # `sum` is rounded to 6 decimals on the wire — exact otherwise
        assert merged.total == pytest.approx(truth.total, abs=1e-5)
        assert merged.min == truth.min and merged.max == truth.max
        for q in (0.5, 0.95, 0.99):
            assert merged.quantile(q) == truth.quantile(q)
        assert out["adapt_ms_p99"] == truth.quantile(0.99)
        assert out["tenants"] == len(values_a) + len(values_b)
        assert len(out["per_host"]) == 2
        # the queue hists merged independently (2x the adapt values)
        qtruth = LogHistogram()
        for v in values_a + values_b:
            qtruth.observe(v * 2.0)
        qmerged = LogHistogram.from_dict(out["queue_ms_hist"])
        assert qmerged.counts == qtruth.counts
        # and the schema-v13 rollup record landed in the sink
        recs = [r for r in sink.records if r.get("event") == "rollup"]
        assert len(recs) == 1 and recs[0]["kind"] == "gateway"
        assert recs[0]["hosts"] == 2 and recs[0]["ready_hosts"] == 2
        from howtotrainyourmamlpytorch_tpu.telemetry import (
            schema as tel,
        )

        tel.validate_record(json.loads(json.dumps(recs[0])))
    finally:
        gateway.close()
        host_a.close()
        host_b.close()


# -- offline readers over fleet logs -----------------------------------------


def test_slo_cli_fleet_merges_host_logs(tmp_path, capsys):
    """`cli slo --fleet GATEWAY_LOG` auto-discovers the .hostNN.
    sibling logs, merges their deadline records into one replay, and
    reports per HOST."""
    from howtotrainyourmamlpytorch_tpu.telemetry.sinks import make_record
    from howtotrainyourmamlpytorch_tpu.tools import slo_cli

    base = tmp_path / "fleet.jsonl"
    base.write_text(json.dumps(make_record(
        "gateway", event="rollup", hosts=2, admitted=5,
    )) + "\n")
    for hid, n_missed, n_ok in (("host00", 1, 2), ("host01", 0, 2)):
        with open(tmp_path / f"fleet.{hid}.jsonl", "w") as f:
            for i in range(n_ok):
                f.write(json.dumps(make_record(
                    "serving", event="deadline", tenant_id=f"t{i}",
                    deadline_ms=50.0, slack_ms=30.0, missed=False,
                    e2e_ms=20.0, replica_id=0,
                )) + "\n")
            for i in range(n_missed):
                f.write(json.dumps(make_record(
                    "serving", event="deadline", tenant_id=f"m{i}",
                    deadline_ms=50.0, slack_ms=-10.0, missed=True,
                    e2e_ms=60.0, replica_id=0,
                )) + "\n")
    assert slo_cli.main(["--fleet", str(base), "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["slo"]["requests"] == 5
    assert payload["slo"]["missed"] == 1
    assert payload["per_host"]["host00"] == {
        "requests": 3, "missed": 1,
    }
    assert payload["per_host"]["host01"] == {
        "requests": 2, "missed": 0,
    }
    # several explicit paths work too, and the text report is per host
    assert slo_cli.main([
        "--fleet",
        str(tmp_path / "fleet.host00.jsonl"),
        str(tmp_path / "fleet.host01.jsonl"),
    ]) == 0
    out = capsys.readouterr().out
    assert "host host00: 3 request(s), 1 missed" in out
    # without --fleet, several logs are refused loudly
    assert slo_cli.main([
        str(tmp_path / "fleet.host00.jsonl"),
        str(tmp_path / "fleet.host01.jsonl"),
    ]) == 2


def test_inspect_summary_renders_fleet_line(tmp_path, capsys):
    """`cli inspect summary` renders the v13 fleet line (hosts, shed
    counts, re-homes) — and pre-v13 logs render without one, never a
    crash."""
    from howtotrainyourmamlpytorch_tpu.telemetry.sinks import make_record
    from howtotrainyourmamlpytorch_tpu.tools import telemetry_cli

    log = tmp_path / "gw.jsonl"
    with open(log, "w") as f:
        f.write(json.dumps(make_record(
            "gateway", event="shed", reason="admission", host="host00",
            tenant_id="t1", priority=0,
        )) + "\n")
        f.write(json.dumps(make_record(
            "gateway", event="rehome", host="host02",
            cause="ConnectionRefusedError(111, 'Connection refused')",
            in_flight=1,
        )) + "\n")
        f.write(json.dumps(make_record(
            "gateway", event="rollup", hosts=3, ready_hosts=2,
            tripped_hosts=["host02"], admitted=40,
            shed={"admission": 2, "deadline": 1}, rehomes=1,
            tenants=40, dispatches=35, adapt_ms_p99=12.5,
        )) + "\n")
    assert telemetry_cli.main(["summary", str(log)]) == 0
    out = capsys.readouterr().out
    assert "fleet: 3 host(s) (2 ready), 40 admitted" in out
    assert "3 shed (2 admission, 1 deadline)" in out
    assert "1 re-home(s)" in out and "adapt p99 12.50ms" in out
    assert "fleet[tripped]: host02" in out
    assert "fleet[rehome]: host02 (1 in flight)" in out
    assert telemetry_cli.main(["summary", str(log), "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["fleet"]["shed_total"] == 3
    assert payload["fleet"]["rehomes"] == 1
    # pre-v13 log: no fleet line, exit 0
    fixture = os.path.join(
        os.path.dirname(__file__), "fixtures",
        "telemetry_v12_schema.jsonl",
    )
    assert telemetry_cli.main(["summary", fixture]) == 0
    assert "fleet:" not in capsys.readouterr().out
